"""Op-DAG streaming execution for ray_tpu.data.

Reference: python/ray/data/_internal/execution — StreamingExecutor
(streaming_executor.py:52, scheduling loop at :277-352), physical
operators (operators/), and the default actor-pool autoscaler
(autoscaler/default_autoscaler.py).

Redesign notes (why this is not the generator chain it replaces):

* Every logical stage becomes a **physical operator** with bounded
  input/output block-ref queues. All operators run *concurrently*: a
  slow sink backpressures upstream through its queue bounds instead of
  serializing the whole pipeline behind one pull.
* A central scheduling loop picks, each tick, the runnable operator
  with the smallest output queue whose launch fits its
  ``ResourceManager`` reservation + shared-pool borrow
  (data/planner.py) — output-queue-aware scheduling keeps the pipeline
  balanced instead of letting a fast producer flood the store.
* ``ExecutionBudget.store_bytes`` is enforced here: the bytes resident
  in operator queues are accounted against the budget and launches are
  gated on headroom, so peak object-store usage is bounded even with a
  deliberately slow consumer.
* Actor-pool map operators autoscale per dataset: sustained input-queue
  depth grows the pool, an empty queue drains it back (idle-first,
  never under a running task), with the hysteresis/cooldown/bounded-
  step discipline proven in serve/_autoscaling.py.

The legacy generator-chain path survives for one PR behind
``RAY_TPU_DATA_LEGACY_EXEC=1`` (see dataset._exec_stream).
"""

from ray_tpu.data._execution.interfaces import PhysicalOperator, RefBundle
from ray_tpu.data._execution.streaming_executor import (
    StreamingExecutor,
    execute_plan,
    recent_execution_summaries,
)

__all__ = [
    "PhysicalOperator",
    "RefBundle",
    "StreamingExecutor",
    "execute_plan",
    "recent_execution_summaries",
]
