"""Physical operators: InputDataBuffer, task-pool and actor-pool map
operators, OutputSplitter.

Reference: python/ray/data/_internal/execution/operators/
(input_data_buffer.py, task_pool_map_operator.py,
actor_pool_map_operator.py, output_splitter.py). Redesign: map tasks
return ``(block, metadata)`` as two objects so the driver learns row
and byte counts from a tiny metadata get — never a payload pull — and
the byte counts feed the ExecutionBudget.store_bytes accounting.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data._execution.autoscaler import PoolAutoscalerPolicy
from ray_tpu.data._execution.interfaces import PhysicalOperator, RefBundle
from ray_tpu.data.block import BlockMetadata
from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class InputDataBuffer(PhysicalOperator):
    """Feeds the DAG. Driver-side sources (``_Source``) are pulled one
    block per launch and put into the store with exact metadata;
    pre-materialized refs (``_RefSource``) stream through with unknown
    sizes (counting 0 toward the byte budget — those blocks are already
    resident, the budget governs what this execution *adds*)."""

    def __init__(self, source: Any, rm: Any):
        super().__init__(getattr(source, "name", "Input"), window=4,
                         max_outqueue=4)
        self._source = source
        self._rm = rm
        self._iter = None
        self._ref_iter = None
        self._exhausted = False
        self.inputs_done = True  # nothing upstream of an input buffer

    def _ensure_started(self) -> None:
        if self._iter is not None or self._ref_iter is not None:
            return
        # _RefSource thunks (shuffle/repartition/join) resolve here —
        # lazily, on first pull, exactly like the legacy path (the
        # iterator may itself be a nested streaming execution).
        if hasattr(self._source, "resolve_refs"):
            self._ref_iter = iter(self._source.resolve_refs())
        else:
            self._iter = self._source.make_blocks()

    def can_launch(self) -> bool:
        return (not self._exhausted
                and len(self.outqueue) < self.max_outqueue)

    def launch_one(self) -> None:
        import ray_tpu

        self._ensure_started()
        if self._ref_iter is not None:
            try:
                self._emit(RefBundle(next(self._ref_iter)))
            except StopIteration:
                self._exhausted = True
            return
        try:
            block = next(self._iter)
        except StopIteration:
            self._exhausted = True
            return
        meta = BlockMetadata.of(block)
        bundle = RefBundle(ray_tpu.put(block), num_rows=meta.num_rows,
                           size_bytes=meta.size_bytes)
        self._rm.on_bytes_acquired(bundle.bytes_or(0))
        self._emit(bundle)

    def exhausted(self) -> bool:
        return self._exhausted


class _MapOperatorBase(PhysicalOperator):
    """Shared machinery for task/actor map operators: ordered emission
    (results surface in input order, matching the legacy generator
    chain), tiny-metadata harvesting, and budget byte accounting."""

    is_map = True

    def __init__(self, name: str, rm: Any, **kw):
        super().__init__(name, **kw)
        self._rm = rm
        self._next_idx = 0       # submission order
        self._emit_idx = 0       # next index owed to the outqueue
        # idx -> {"out": ref, "meta": ref, "in": RefBundle, ...}
        self._pending: Dict[int, Dict[str, Any]] = {}
        # idx -> RefBundle completed but waiting for earlier indices
        self._ready: Dict[int, RefBundle] = {}

    def num_inflight(self) -> int:
        return len(self._pending)

    def pending_outputs(self) -> int:
        return len(self._pending) + len(self._ready)

    def can_launch(self) -> bool:
        return bool(self.inqueue)

    def _track(self, out_ref: Any, meta_ref: Any, in_bundle: RefBundle,
               **extra: Any) -> None:
        entry = {"out": out_ref, "meta": meta_ref, "in": in_bundle}
        entry.update(extra)
        self._pending[self._next_idx] = entry
        self._next_idx += 1
        self._rm.on_launch(self)
        self.peak_inflight = max(self.peak_inflight, len(self._pending))

    def meta_refs(self) -> List[Any]:
        return [e["meta"] for e in self._pending.values()]

    def poll(self) -> bool:
        if not self._pending:
            return False
        import ray_tpu

        metas = [e["meta"] for e in self._pending.values()]
        ready, _ = ray_tpu.wait(metas, num_returns=len(metas), timeout=0)
        if not ready:
            return False
        ready_ids = {r.id.binary() for r in ready}
        progressed = False
        for idx in sorted(self._pending):
            e = self._pending[idx]
            if e["meta"].id.binary() not in ready_ids:
                continue
            del self._pending[idx]
            self._on_task_done(e)
            try:
                meta = ray_tpu.get(e["meta"])
                bundle = RefBundle(e["out"], num_rows=meta["rows"],
                                   size_bytes=meta["bytes"])
            except Exception:  # noqa: BLE001 - the task raised: the error
                # value is stored in the block ref too, so surface it to
                # the consumer exactly like the legacy path (on get).
                bundle = RefBundle(e["out"])
            self._rm.on_complete(self)
            # The input block ref is dropped with this entry: its bytes
            # leave the execution's resident set, the output's enter.
            self._rm.on_bytes_released(e["in"].bytes_or(0))
            self._rm.on_bytes_acquired(bundle.bytes_or(0))
            self._ready[idx] = bundle
            progressed = True
        while self._emit_idx in self._ready:
            self._emit(self._ready.pop(self._emit_idx))
            self._emit_idx += 1
        return progressed

    def _on_task_done(self, entry: Dict[str, Any]) -> None:
        pass

    def exhausted(self) -> bool:
        return (self.inputs_done and not self.inqueue
                and not self._pending and not self._ready)


class TaskPoolMapOperator(_MapOperatorBase):
    """Stateless transform: one ray_tpu task per block (reference:
    task_pool_map_operator.py)."""

    def __init__(self, logical_op: Any, rm: Any):
        super().__init__(getattr(logical_op, "name", "MapBatches"), rm,
                         num_cpus=getattr(logical_op, "num_cpus", 1.0),
                         window=getattr(logical_op, "window", 4))
        self._logical = logical_op
        import ray_tpu

        @ray_tpu.remote
        def _run(block, op=logical_op):
            from ray_tpu.data.dataset import _apply_map_batches

            out = _apply_map_batches(op, block)
            m = BlockMetadata.of(out)
            return out, {"rows": m.num_rows, "bytes": m.size_bytes}

        self._remote = _run.options(num_cpus=self.num_cpus, num_returns=2)

    def launch_one(self) -> None:
        bundle = self.inqueue.popleft()
        out_ref, meta_ref = self._remote.remote(bundle.ref)
        self._track(out_ref, meta_ref, bundle)


class ActorPoolMapOperator(_MapOperatorBase):
    """Stateful transform over an autoscaling pool of actors (reference:
    actor_pool_map_operator.py + autoscaler/default_autoscaler.py). The
    expensive constructor runs once per actor; the pool grows on
    sustained input-queue depth and drains back (idle-first) when the
    queue empties."""

    def __init__(self, logical_op: Any, rm: Any,
                 on_scale_event: Optional[Callable[[str], None]] = None):
        min_size = max(1, int(getattr(logical_op, "concurrency", 1)))
        max_size = max(min_size,
                       int(getattr(logical_op, "max_concurrency", None)
                           or min_size))
        per_actor = max(1, int(getattr(logical_op, "window_per_actor", 2)))
        # The ``window`` property below reads these — set them before the
        # base __init__ touches self.window.
        self._per_actor = per_actor
        self._pool: List[Dict[str, Any]] = []  # [{"handle", "inflight"}]
        super().__init__(
            getattr(logical_op, "name", "MapBatches(actors)"), rm,
            num_cpus=getattr(logical_op, "num_cpus", 1.0),
            window=max_size * per_actor,
            max_inqueue=max(4, 2 * per_actor * max_size),
            max_outqueue=max(2, per_actor * max_size))
        self._logical = logical_op
        self._policy = PoolAutoscalerPolicy(
            min_size, max_size,
            getattr(logical_op, "autoscale_config", None))
        self._on_scale_event = on_scale_event or (lambda direction: None)
        self.pool_size_peak = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self._actor_cls = self._build_actor_cls()
        for _ in range(min_size):
            self._add_actor()

    # The backpressure chain reads ``window`` as the concurrency cap;
    # for a pool it is what the *current* pool can hold in flight.
    @property
    def window(self) -> int:
        return max(1, len(self._pool) * self._per_actor)

    @window.setter
    def window(self, value: int) -> None:
        pass  # base-class __init__ assignment; pool size is the truth

    def _build_actor_cls(self):
        import ray_tpu
        from ray_tpu.data.block import (
            block_as_format,
            block_concat,
            iter_block_batches,
            normalize_batch_output,
        )

        op = self._logical
        cls, batch_size = op.cls, op.batch_size
        fn_kwargs = op.fn_kwargs or {}
        fmt = op.batch_format
        ctor_args = op.fn_constructor_args
        ctor_kwargs = op.fn_constructor_kwargs or {}

        @ray_tpu.remote
        class _BatchWorker:
            def __init__(self):
                self.inst = cls(*ctor_args, **ctor_kwargs)

            def run(self, block):
                outs = []
                for batch in iter_block_batches(block, batch_size):
                    outs.append(normalize_batch_output(
                        self.inst(block_as_format(batch, fmt),
                                  **fn_kwargs)))
                out = block_concat(outs) if outs else {}
                m = BlockMetadata.of(out)
                return out, {"rows": m.num_rows, "bytes": m.size_bytes}

        return _BatchWorker.options(
            num_cpus=op.num_cpus,
            num_tpus=getattr(op, "num_tpus", 0.0))

    def _add_actor(self) -> None:
        self._pool.append({"handle": self._actor_cls.remote(),
                           "inflight": 0})
        self.pool_size_peak = max(self.pool_size_peak, len(self._pool))

    def pool_size(self) -> int:
        return len(self._pool)

    def idle_actors(self) -> int:
        return sum(1 for a in self._pool if a["inflight"] == 0)

    def can_launch(self) -> bool:
        return bool(self.inqueue) and any(
            a["inflight"] < self._per_actor for a in self._pool)

    def launch_one(self) -> None:
        bundle = self.inqueue.popleft()
        slot = min((a for a in self._pool
                    if a["inflight"] < self._per_actor),
                   key=lambda a: a["inflight"])
        slot["inflight"] += 1
        out_ref, meta_ref = slot["handle"].run.options(
            num_returns=2).remote(bundle.ref)
        self._track(out_ref, meta_ref, bundle, slot=slot)

    def _on_task_done(self, entry: Dict[str, Any]) -> None:
        slot = entry.get("slot")
        if slot is not None and slot["inflight"] > 0:
            slot["inflight"] -= 1

    def maybe_autoscale(self, now: float) -> None:
        delta = self._policy.tick(now, queued=len(self.inqueue),
                                  pool_size=len(self._pool),
                                  idle=self.idle_actors())
        if delta > 0:
            for _ in range(delta):
                self._add_actor()
            self.scale_ups += 1
            self._on_scale_event("up")
            logger.debug("data actor pool %s scaled up to %d",
                         self.name, len(self._pool))
        elif delta < 0:
            import ray_tpu

            killed = 0
            for slot in [a for a in self._pool if a["inflight"] == 0]:
                if killed >= -delta:
                    break
                self._pool.remove(slot)
                try:
                    ray_tpu.kill(slot["handle"])
                except Exception:  # noqa: BLE001
                    pass
                killed += 1
            if killed:
                self.scale_downs += 1
                self._on_scale_event("down")
                logger.debug("data actor pool %s drained down to %d",
                             self.name, len(self._pool))

    def shutdown(self) -> None:
        import ray_tpu

        for slot in self._pool:
            try:
                ray_tpu.kill(slot["handle"])
            except Exception:  # noqa: BLE001
                pass
        self._pool.clear()

    def stat_row(self) -> Dict[str, Any]:
        row = super().stat_row()
        row.update({
            "pool_size": len(self._pool),
            "pool_size_peak": self.pool_size_peak,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
        })
        return row


class OutputSplitter(PhysicalOperator):
    """Deals finished bundles round-robin to N consumer queues
    (reference: output_splitter.py behind streaming_split). Dealt
    bundles leave the execution's byte accounting — the per-split
    queues are consumer-owned buffers, and blocking the deal on one
    slow split would deadlock the others (the round-robin contract
    means every split's next block may sit behind a block owed to a
    slower split)."""

    def __init__(self, n: int, rm: Any):
        super().__init__(f"OutputSplitter({n})", window=1)
        self.n = max(1, int(n))
        self._rm = rm
        self.split_queues: List[List[RefBundle]] = [[] for _ in range(self.n)]
        self._rr = 0

    def can_accept_input(self) -> bool:
        return True  # dealing is unbounded; see class docstring

    def poll(self) -> bool:
        progressed = False
        while self.inqueue:
            bundle = self.inqueue.popleft()
            self.split_queues[self._rr].append(bundle)
            self._rr = (self._rr + 1) % self.n
            self._rm.on_bytes_released(bundle.bytes_or(0))
            self.blocks_out += 1
            if bundle.num_rows is not None:
                self.rows_out += bundle.num_rows
            progressed = True
        return progressed


def estimate_output_rate(op: PhysicalOperator,
                         started_at: float) -> float:
    dt = max(1e-6, time.monotonic() - started_at)
    return op.rows_out / dt
