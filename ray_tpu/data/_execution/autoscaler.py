"""Per-dataset actor-pool autoscaler policy.

Reference: python/ray/data/_internal/execution/autoscaler/
default_autoscaler.py (scale an ActorPoolMapOperator on input-queue
pressure / idle actors). The flap-control discipline — hysteresis delay
windows, post-decision cooldowns, bounded per-cycle step, min/max
clamps — is the one proven in serve/_autoscaling.py; this is the data
plane's instance of it, driven by block queues instead of request
gauges.

Pure in-process state with explicit ``now`` so every branch is
unit-testable without a cluster or sleeps.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

DEFAULTS: Dict[str, float] = {
    # Scale up when the input queue holds more than this many blocks
    # per actor (work the pool cannot have in flight), sustained.
    "up_queue_per_actor": 1.0,
    "up_delay_s": 0.2,
    "down_delay_s": 0.5,
    # Refractory period after an applied decision, so actor boot/drain
    # latency never double-fires.
    "up_cooldown_s": 0.2,
    "down_cooldown_s": 0.3,
    # Bounded actuation: one tick never adds/removes more than this.
    "max_step": 1,
}


class PoolAutoscalerPolicy:
    """Decides pool-size deltas for one actor-pool operator.

    ``tick`` returns +k to grow, -k to shrink (only ever up to the
    number of *idle* actors — scale-down is drain-based: a running task
    is never killed under an actor), or 0."""

    def __init__(self, min_size: int, max_size: int,
                 config: Optional[Dict[str, Any]] = None):
        cfg = dict(DEFAULTS)
        cfg.update(config or {})
        self.min_size = max(1, int(min_size))
        self.max_size = max(self.min_size, int(max_size))
        self.up_queue_per_actor = float(cfg["up_queue_per_actor"])
        self.up_delay_s = float(cfg["up_delay_s"])
        self.down_delay_s = float(cfg["down_delay_s"])
        self.up_cooldown_s = float(cfg["up_cooldown_s"])
        self.down_cooldown_s = float(cfg["down_cooldown_s"])
        self.max_step = max(1, int(cfg["max_step"]))
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._cooldown_until = 0.0

    def tick(self, now: float, *, queued: int, pool_size: int,
             idle: int) -> int:
        want_up = (queued > pool_size * self.up_queue_per_actor
                   and pool_size < self.max_size)
        want_down = (queued == 0 and idle > 0
                     and pool_size > self.min_size)
        if want_up:
            self._down_since = None
            if self._up_since is None:
                self._up_since = now
            if (now >= self._cooldown_until
                    and now - self._up_since >= self.up_delay_s):
                self._up_since = None
                self._cooldown_until = now + self.up_cooldown_s
                return min(self.max_step, self.max_size - pool_size)
        elif want_down:
            self._up_since = None
            if self._down_since is None:
                self._down_since = now
            if (now >= self._cooldown_until
                    and now - self._down_since >= self.down_delay_s):
                self._down_since = None
                self._cooldown_until = now + self.down_cooldown_s
                # Drain-based: never shrink past what is provably idle.
                return -min(self.max_step, idle,
                            pool_size - self.min_size)
        else:
            self._up_since = self._down_since = None
        return 0
