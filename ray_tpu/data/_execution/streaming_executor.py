"""The streaming scheduling loop.

Reference: python/ray/data/_internal/execution/streaming_executor.py:52
(scheduling loop at :277-352) and streaming_executor_state.py
(select_operator_to_run: prefer the runnable operator with the smallest
output queue). Redesign: **pump-on-pull** instead of a background
scheduler thread. ``next_output()`` runs scheduling ticks inline until
the sink has a block; between pulls, in-flight tasks keep progressing in
workers. No thread means the executor is safe inside actor processes
(the streaming_split coordinator runs one) and exceptions surface on
the consumer's stack, not a daemon's.

Each tick:
  1. poll every operator (harvest finished tasks → output queues),
  2. flow outputs downstream through bounded input queues and propagate
     end-of-input,
  3. autoscale actor pools,
  4. repeatedly launch on the runnable operator with the smallest
     output queue whose launch fits its ResourceManager reservation +
     shared-pool borrow — and the execution's store-byte budget.

Budget gating (ExecutionBudget.store_bytes): when the resident-byte
headroom is exhausted, only the operator **deepest in the DAG** with
pending input may launch — consuming toward the sink is what frees
bytes, so drain must never be blocked by the very pressure it relieves
(the classic budget deadlock when one block exceeds the budget).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional

from ray_tpu.data._execution.interfaces import PhysicalOperator, RefBundle
from ray_tpu.data._execution.operators import (
    ActorPoolMapOperator,
    InputDataBuffer,
    OutputSplitter,
    TaskPoolMapOperator,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Metric names (also asserted by scripts/check_metrics_contract.py —
# keep as plain string literals).
_M_ROWS = "ray_tpu_data_op_output_rows_total"
_M_BLOCKS = "ray_tpu_data_op_output_blocks_total"
_M_QUEUED = "ray_tpu_data_op_queued_blocks"
_M_INFLIGHT = "ray_tpu_data_op_inflight_tasks"
_M_POOL = "ray_tpu_data_actor_pool_size"
_M_BYTES = "ray_tpu_data_queued_bytes"
_M_AUTOSCALE = "ray_tpu_data_autoscale_events_total"

_METRICS_PERIOD_S = 0.25
_STALL_TIMEOUT_S = float(os.environ.get("RAY_TPU_DATA_STALL_S", "60"))

# Ring of finished-execution summaries, newest last
# (ray_tpu.data.execution_summaries() is the public accessor).
_RECENT: Deque[Dict[str, Any]] = deque(maxlen=32)


def recent_execution_summaries() -> List[Dict[str, Any]]:
    return list(_RECENT)


class StreamingExecutor:
    """Executes one fused logical plan as a DAG of physical operators.

    ``split_n``: terminate the DAG in an OutputSplitter dealing to that
    many consumer queues (streaming_split); otherwise the last
    operator's output queue is the sink.
    """

    def __init__(self, plan: List[Any], budget: Any = None,
                 split_n: Optional[int] = None):
        from ray_tpu.data import planner
        from ray_tpu.data.dataset import (
            _MapBatches,
            _MapBatchesActor,
            _fuse_plan,
        )

        plan = _fuse_plan(plan)
        self._rm = planner.ResourceManager(
            budget or planner.default_execution_budget())
        self.ops: List[PhysicalOperator] = [
            InputDataBuffer(plan[0], self._rm)]
        for logical in plan[1:]:
            if isinstance(logical, _MapBatchesActor):
                self.ops.append(ActorPoolMapOperator(
                    logical, self._rm,
                    on_scale_event=self._record_autoscale))
            elif isinstance(logical, _MapBatches):
                self.ops.append(TaskPoolMapOperator(logical, self._rm))
            else:
                raise TypeError(
                    f"unknown logical op in plan: {logical!r}")
        self.splitter: Optional[OutputSplitter] = None
        if split_n is not None:
            self.splitter = OutputSplitter(split_n, self._rm)
            self.ops.append(self.splitter)
        # Reservations are split among ops that actually hold cpu slots.
        self._rm.register_ops([op for op in self.ops if op.is_map])
        self.sink = self.ops[-1]
        self.dataset_tag = self.sink.name if self.splitter is None \
            else self.ops[-2].name
        self.max_concurrent_ops = 0
        self._autoscale_events = 0
        self._started_at = time.monotonic()
        self._last_progress = time.monotonic()
        self._last_metrics = 0.0
        self._shutdown = False
        self._metrics = self._make_metrics()

    # -- telemetry ------------------------------------------------------
    def _make_metrics(self) -> Dict[str, Any]:
        from ray_tpu.util.metrics import get_counter, get_gauge

        return {
            "rows": get_counter(_M_ROWS,
                                "rows emitted per data operator"),
            "blocks": get_counter(_M_BLOCKS,
                                  "blocks emitted per data operator"),
            "queued": get_gauge(_M_QUEUED,
                                "blocks waiting in operator input queues"),
            "inflight": get_gauge(_M_INFLIGHT,
                                  "tasks in flight per data operator"),
            "pool": get_gauge(_M_POOL, "actor-pool size per data operator"),
            "bytes": get_gauge(_M_BYTES,
                               "bytes resident in execution queues"),
            "autoscale": get_counter(
                _M_AUTOSCALE, "data actor-pool scale up/down events"),
        }

    def _record_autoscale(self, direction: str) -> None:
        self._autoscale_events += 1
        self._metrics["autoscale"].inc(
            1.0, tags={"dataset": self.dataset_tag, "direction": direction})

    def _publish_metrics(self, now: float, final: bool = False) -> None:
        if not final and now - self._last_metrics < _METRICS_PERIOD_S:
            return
        self._last_metrics = now
        m = self._metrics
        for op in self.ops:
            tags = {"dataset": self.dataset_tag, "op": op.name}
            emitted = op.blocks_out - getattr(op, "_pub_blocks", 0)
            if emitted:
                m["blocks"].inc(emitted, tags=tags)
                op._pub_blocks = op.blocks_out
            rows = op.rows_out - getattr(op, "_pub_rows", 0)
            if rows:
                m["rows"].inc(rows, tags=tags)
                op._pub_rows = op.rows_out
            m["queued"].set(0 if final else len(op.inqueue), tags=tags)
            m["inflight"].set(0 if final else op.num_inflight(), tags=tags)
            if isinstance(op, ActorPoolMapOperator):
                m["pool"].set(0 if final else op.pool_size(), tags=tags)
        m["bytes"].set(0 if final else self._rm.held_bytes,
                       tags={"dataset": self.dataset_tag})

    # -- the scheduling tick --------------------------------------------
    def _flow(self) -> bool:
        moved = False
        for up, down in zip(self.ops, self.ops[1:]):
            while up.outqueue and down.can_accept_input():
                down.add_input(up.outqueue.popleft())
                moved = True
            if up.exhausted() and not up.outqueue and not down.inputs_done:
                down.mark_inputs_done()
                moved = True
        return moved

    def _launchable(self, op: PhysicalOperator) -> bool:
        if not op.can_launch():
            return False
        if len(op.outqueue) + op.pending_outputs() >= op.max_outqueue:
            return False
        if op.is_map:
            from ray_tpu.data.planner import effective_window

            if op.num_inflight() >= effective_window(op):
                return False
        headroom = self._rm.store_headroom()
        if headroom is not None and headroom <= 0:
            # Budget exhausted: drain toward the sink only. The deepest
            # op with pending input nets bytes out of the execution
            # fastest; producing new input is what got us here.
            deepest = None
            for candidate in self.ops:
                if candidate.is_map and candidate.can_launch():
                    deepest = candidate
            if deepest is not None:
                return op is deepest
            # No map op can drain. Allow the input buffer only when the
            # execution holds nothing at all — otherwise a budget
            # smaller than one block would deadlock before the first
            # block ever flows.
            return isinstance(op, InputDataBuffer) and all(
                not o.inqueue and not o.outqueue and o.num_inflight() == 0
                and o.pending_outputs() == 0 for o in self.ops)
        return True

    def _tick(self) -> bool:
        progressed = False
        for op in self.ops:
            if op.poll():
                progressed = True
        if self._flow():
            progressed = True
        now = time.monotonic()
        for op in self.ops:
            if isinstance(op, ActorPoolMapOperator):
                op.maybe_autoscale(now)
        # Launch loop: repeatedly pick the runnable op with the smallest
        # output queue (bytes, then blocks owed) — the starved end of
        # the pipeline — until nothing fits.
        while True:
            candidates = [op for op in self.ops if self._launchable(op)]
            if not candidates:
                break
            op = min(candidates, key=lambda o: (
                o.outqueue_bytes(),
                len(o.outqueue) + o.pending_outputs()))
            op.launch_one()
            progressed = True
        busy = sum(1 for op in self.ops if op.num_inflight() > 0)
        self.max_concurrent_ops = max(self.max_concurrent_ops, busy)
        self._publish_metrics(now)
        if progressed:
            self._last_progress = now
        return progressed

    def _wait_for_any(self) -> None:
        """Block briefly for any in-flight task (metadata-only wait —
        payloads are never pulled by the scheduler)."""
        import ray_tpu

        metas: List[Any] = []
        for op in self.ops:
            if op.is_map:
                metas.extend(op.meta_refs())
        if metas:
            try:
                ray_tpu.wait(metas, num_returns=1, timeout=0.05)
                return
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.002)

    def _pump_until(self, cond) -> None:
        # Always run at least one tick, even when output is already
        # buffered: pulls are the executor's only clock (no background
        # thread), so refill/autoscale/metrics must advance per pull or
        # a pre-filled sink queue would freeze the rest of the pipeline
        # until it drained.
        first = True
        while first or not cond():
            first = False
            progressed = self._tick()
            if cond():
                return
            if self._finished():
                return
            if not progressed:
                if (time.monotonic() - self._last_progress
                        > _STALL_TIMEOUT_S):
                    states = ", ".join(repr(op) for op in self.ops)
                    raise RuntimeError(
                        f"data execution stalled for "
                        f">{_STALL_TIMEOUT_S:.0f}s "
                        f"(held_bytes={self._rm.held_bytes}, "
                        f"budget={self._rm.budget.store_bytes}): {states}")
                self._wait_for_any()

    def _finished(self) -> bool:
        return all(op.exhausted() for op in self.ops)

    # -- consumer API ---------------------------------------------------
    def next_output(self) -> Any:
        """Next sink block ref, in input order. Raises StopIteration
        when the plan is exhausted."""
        self._pump_until(lambda: bool(self.sink.outqueue))
        if not self.sink.outqueue:
            raise StopIteration
        bundle = self.sink.outqueue.popleft()
        # Handing the block to the consumer ends this execution's claim
        # on its bytes.
        self._rm.on_bytes_released(bundle.bytes_or(0))
        return bundle.ref

    def next_for_split(self, split_idx: int) -> Any:
        """Next block ref for one streaming_split consumer. Raises
        StopIteration when that split's stream is exhausted."""
        assert self.splitter is not None, "executor not built with split_n"
        q = self.splitter.split_queues[split_idx]
        self._pump_until(lambda: bool(q))
        if not q:
            raise StopIteration
        return q.pop(0).ref

    def iter_outputs(self) -> Iterator[Any]:
        try:
            while True:
                try:
                    yield self.next_output()
                except StopIteration:
                    return
        finally:
            self.shutdown()

    # -- lifecycle ------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        return {
            "dataset": self.dataset_tag,
            "duration_s": time.monotonic() - self._started_at,
            "max_concurrent_ops": self.max_concurrent_ops,
            "peak_held_bytes": self._rm.peak_held_bytes,
            "store_bytes_budget": self._rm.budget.store_bytes,
            "autoscale_events": self._autoscale_events,
            "ops": [dict(op.stat_row(), name=op.name) for op in self.ops],
        }

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        self._publish_metrics(time.monotonic(), final=True)
        _RECENT.append(self.summary())
        for op in self.ops:
            try:
                op.shutdown()
            except Exception:  # noqa: BLE001
                logger.exception("operator %s shutdown failed", op.name)

    def __del__(self):
        try:
            self.shutdown()
        except Exception:  # noqa: BLE001
            pass


def execute_plan(plan: List[Any], budget: Any = None) -> Iterator[Any]:
    """Plan → iterator of sink block ObjectRefs on the streaming
    executor (the non-split entry point dataset._exec_stream uses)."""
    return StreamingExecutor(plan, budget=budget).iter_outputs()
