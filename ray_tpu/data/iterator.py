"""DataIterator + split coordination for Train ingest.

Reference: python/ray/data/iterator.py (`DataIterator.iter_batches`) and the
streaming_split SplitCoordinator actor
(_internal/execution/operators/output_splitter.py). Redesign: the coordinator
is a plain actor running the streaming executor; consumers pull block refs
round-robin with per-split buffering — pulling is the backpressure.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.data.block import (
    Block,
    block_concat,
    block_num_rows,
    block_slice,
)


class _SplitCoordinator:
    """Actor: executes the plan once per epoch, dealing blocks to n splits
    round-robin.

    Default path: a StreamingExecutor terminated in an OutputSplitter —
    the pump-on-pull loop runs inside this actor process (no background
    thread), and the splitter deals eagerly into per-split queues so one
    far-behind consumer never stalls the others (the dealt blocks leave
    the execution's byte budget; see OutputSplitter). Legacy generator
    path behind RAY_TPU_DATA_LEGACY_EXEC=1 keeps shallow shared-advance
    queues."""

    def __init__(self, plan: List[Any], n: int):
        self._plan = plan
        self._n = n
        self._epoch = 0
        self._exec = None  # StreamingExecutor (default path)
        # Legacy-path state.
        self._queues: List[List[Any]] = [[] for _ in range(n)]
        self._stream = None
        self._exhausted = False
        self._rr = 0

    @staticmethod
    def _use_legacy() -> bool:
        import os

        return os.environ.get("RAY_TPU_DATA_LEGACY_EXEC") == "1"

    def _ensure_stream(self):
        if self._use_legacy():
            if self._stream is None:
                from ray_tpu.data.dataset import _exec_stream_legacy

                self._stream = _exec_stream_legacy(self._plan)
        elif self._exec is None:
            from ray_tpu.data._execution import StreamingExecutor

            self._exec = StreamingExecutor(self._plan, split_n=self._n)

    def next_block(self, split_idx: int) -> Optional[Block]:
        """Returns the next block for split i (as a value — task-result
        ownership transfers it to the caller; handing out raw refs would race
        the coordinator's ref-count drop against the consumer's borrow)."""
        import ray_tpu

        self._ensure_stream()
        if self._exec is not None:
            try:
                ref = self._exec.next_for_split(split_idx)
            except StopIteration:
                return None
            return ray_tpu.get(ref)
        q = self._queues[split_idx]
        while not q and not self._exhausted:
            try:
                ref = next(self._stream)
            except StopIteration:
                self._exhausted = True
                break
            self._queues[self._rr].append(ref)
            self._rr = (self._rr + 1) % self._n
        if q:
            return ray_tpu.get(q.pop(0))
        return None

    def reset(self):
        """Start a fresh epoch (re-runs the plan). Blocks already dealt to
        a split but not yet pulled belong to the finished epoch and are
        discarded — epoch boundaries are the trainer's barrier."""
        if self._exec is not None:
            self._exec.shutdown()
            self._exec = None
        self._stream = None
        self._exhausted = False
        self._queues = [[] for _ in range(self._n)]
        self._rr = 0
        self._epoch += 1

    def epoch(self) -> int:
        return self._epoch

    def stats(self) -> Optional[Dict[str, Any]]:
        """Live executor summary (per-op telemetry breakdown), None on the
        legacy path or before the first pull of an epoch."""
        if self._exec is None:
            return None
        return self._exec.summary()


class DataIterator:
    """Per-consumer iterator; picklable (ships an actor handle or a plan).

    Reference: data/iterator.py — `get_dataset_shard` returns one of these
    inside each train worker."""

    def __init__(self, *, dataset: Any = None, coordinator: Any = None,
                 split_idx: int = 0):
        self._dataset = dataset
        self._coordinator = coordinator
        self._split_idx = split_idx

    def _block_iter(self) -> Iterator[Block]:
        import ray_tpu

        if self._coordinator is not None:
            while True:
                block = ray_tpu.get(
                    self._coordinator.next_block.remote(self._split_idx))
                if block is None:
                    return
                yield block
        else:
            yield from self._dataset.iter_blocks()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Block]:
        leftover: Optional[Block] = None
        for block in self._block_iter():
            if leftover is not None and block_num_rows(leftover):
                block = block_concat([leftover, block])
                leftover = None
            if batch_size is None:
                yield block
                continue
            n = block_num_rows(block)
            i = 0
            while n - i >= batch_size:
                yield block_slice(block, i, i + batch_size)
                i += batch_size
            if i < n:
                leftover = block_slice(block, i, n)
        if (leftover is not None and block_num_rows(leftover)
                and not drop_last):
            yield leftover

    def iter_rows(self) -> Iterator[Any]:
        from ray_tpu.data.block import block_to_items

        for block in self._block_iter():
            yield from block_to_items(block)

    def materialize_all(self) -> List[Block]:
        return list(self._block_iter())

    def new_epoch(self) -> None:
        if self._coordinator is not None and self._split_idx == 0:
            import ray_tpu

            ray_tpu.get(self._coordinator.reset.remote())
