"""Plan optimization rules + execution backpressure policies
(reference: python/ray/data/_internal/logical/optimizers.py — the
rule-based LogicalOptimizer/PhysicalOptimizer pair — and
_internal/execution/backpressure_policy/backpressure_policy.py).

Rules are pure plan→plan rewrites applied in order by the executor;
backpressure policies bound each operator's in-flight task window at
runtime. Both are extension points: `register_rule` /
`register_backpressure_policy` add custom ones process-wide, and a
Dataset can carry its own via `with_rules` (see dataset.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class Rule:
    """One plan rewrite (reference: logical/interfaces.Rule)."""

    name = "rule"

    def apply(self, plan: List[Any]) -> List[Any]:
        raise NotImplementedError


class OperatorFusionRule(Rule):
    """Fuse consecutive task-based map ops into one task (reference:
    _internal/logical/rules/operator_fusion.py). A map→map chain
    otherwise pays one dispatch + one object-store round trip per stage
    per block. Actor ops don't fuse (they pin state to a pool)."""

    name = "operator_fusion"

    def apply(self, plan: List[Any]) -> List[Any]:
        from ray_tpu.data.dataset import _MapBatches

        out: List[Any] = [plan[0]]
        for op in plan[1:]:
            prev = out[-1]
            if (isinstance(op, _MapBatches)
                    and isinstance(prev, _MapBatches)
                    and prev.num_cpus == op.num_cpus):
                stages = list(prev.fused_stages or [prev])
                fused = _MapBatches(
                    fn=None, batch_size=None, num_cpus=op.num_cpus,
                    window=min(prev.window, op.window),
                    name=f"{prev.name}->{op.name}")
                fused.fused_stages = stages + [op]
                out[-1] = fused
                continue
            out.append(op)
        return out


_RULES: List[Rule] = [OperatorFusionRule()]


def register_rule(rule: Rule) -> None:
    _RULES.append(rule)


def get_rules() -> List[Rule]:
    return list(_RULES)


def optimize(plan: List[Any], extra_rules: Any = None) -> List[Any]:
    for rule in list(_RULES) + list(extra_rules or []):
        try:
            plan = rule.apply(plan)
        except Exception:  # noqa: BLE001 - a broken custom rule must not
            logger.exception("plan rule %s failed; skipping", rule.name)
    return plan


# ---------------------------------------------------------------------------
# Backpressure policies
# ---------------------------------------------------------------------------
class BackpressurePolicy:
    """Bounds an operator's in-flight task window (reference:
    backpressure_policy.py — policies can only SHRINK concurrency)."""

    name = "backpressure"

    def max_inflight(self, op: Any) -> int:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """The operator's configured window (reference:
    concurrency_cap_backpressure_policy.py)."""

    name = "concurrency_cap"

    def max_inflight(self, op: Any) -> int:
        return max(1, getattr(op, "window", 4))


class ObjectStoreMemoryBackpressurePolicy(BackpressurePolicy):
    """Shrink windows while the local arena is under pressure: every
    in-flight block pins store space, and racing ahead of a full store
    just converts task throughput into spill churn (reference:
    streaming_output_backpressure / reservation policies)."""

    name = "object_store_memory"

    def __init__(self, high_watermark: float = 0.8):
        self.high_watermark = high_watermark

    def max_inflight(self, op: Any) -> int:
        window = max(1, getattr(op, "window", 4))
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is None:
                return window
            stats = w.shm.stats()
            frac = stats["bytes_in_use"] / max(1, stats["capacity"])
        except Exception:  # noqa: BLE001
            return window
        if frac >= self.high_watermark:
            return 1  # drain mode: one block in flight per operator
        return window


_BP_POLICIES: List[BackpressurePolicy] = [
    ConcurrencyCapBackpressurePolicy(),
    ObjectStoreMemoryBackpressurePolicy(),
]


def register_backpressure_policy(policy: BackpressurePolicy) -> None:
    _BP_POLICIES.append(policy)


def effective_window(op: Any) -> int:
    """The tightest bound across policies (policies only shrink)."""
    window = max(1, getattr(op, "window", 4))
    for policy in _BP_POLICIES:
        try:
            window = min(window, max(1, policy.max_inflight(op)))
        except Exception:  # noqa: BLE001
            continue
    return window


# ---------------------------------------------------------------------------
# Execution resource manager (reference: python/ray/data/_internal/
# execution/resource_manager.py — ResourceManager + the reservation-based
# ReservationOpResourceAllocator: a global execution budget is split into
# per-operator reservations plus a shared pool, and operator concurrency
# is bounded by what its reservation can hold).
# ---------------------------------------------------------------------------

class ExecutionBudget:
    """Global budget one dataset execution may consume.

    ``store_bytes`` caps the bytes an execution keeps resident in the
    object store at once (blocks held in operator queues and in flight);
    the streaming executor gates launches on the remaining headroom.
    None means unbounded."""

    def __init__(self, cpu_slots: Optional[float] = None,
                 store_bytes: Optional[int] = None):
        if cpu_slots is None:
            import os

            cpu_slots = float(os.cpu_count() or 1)
        self.cpu_slots = cpu_slots
        self.store_bytes = store_bytes

    @classmethod
    def default(cls) -> "ExecutionBudget":
        """Budget for executions that don't pass one: store cap from
        RAY_TPU_DATA_STORE_BYTES, else 50% of the local arena capacity
        (one execution should never monopolize the store), else
        unbounded when no store is up."""
        import os

        env = os.environ.get("RAY_TPU_DATA_STORE_BYTES")
        if env:
            try:
                return cls(store_bytes=int(env))
            except ValueError:
                logger.warning("ignoring bad RAY_TPU_DATA_STORE_BYTES=%r",
                               env)
        store_bytes = None
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is not None:
                store_bytes = int(w.shm.stats()["capacity"] * 0.5)
        except Exception:  # noqa: BLE001
            pass
        return cls(store_bytes=store_bytes)


# Process-wide override for the budget new executions default to
# (tests and embedders; executions that pass an explicit budget are
# unaffected).
_default_budget: Optional[ExecutionBudget] = None


def set_default_execution_budget(
        budget: Optional[ExecutionBudget]) -> None:
    global _default_budget
    _default_budget = budget


def default_execution_budget() -> ExecutionBudget:
    return _default_budget or ExecutionBudget.default()


class ResourceManager:
    """Per-execution reservations over the global budget.

    Each operator gets `reservation_frac / n_ops` of the budget
    exclusively; the rest is a shared pool ops borrow from first-come.
    An op's launch window is what its reservation + current shared
    borrow can hold, in units of its per-task cost (cpu) — shrink-only
    against the configured window, like every backpressure policy."""

    def __init__(self, budget: Optional[ExecutionBudget] = None,
                 reservation_frac: float = 0.5):
        self.budget = budget or ExecutionBudget()
        self.reservation_frac = reservation_frac
        self._ops: Dict[int, Dict[str, Any]] = {}
        # Bytes this execution currently keeps resident in the store
        # (operator queues + in-flight inputs), counted against
        # budget.store_bytes by the streaming executor.
        self.held_bytes = 0
        self.peak_held_bytes = 0

    # -- registration ---------------------------------------------------
    def register_ops(self, ops) -> None:
        self._ops.clear()
        for op in ops:
            self._ops[id(op)] = {
                "op": op,
                "inflight": 0,
                "cpu_per_task": max(0.001,
                                    float(getattr(op, "num_cpus", 1.0))),
            }
            # Bind manager→op directly: the reservation policy reads this,
            # so two interleaved dataset executions each keep their own
            # budgets (a process-global contextvar would make the second
            # execution silently unbound the first's ops).
            try:
                op._rt_resource_manager = self
            except Exception:  # slotted/frozen op: falls back to contextvar
                pass

    def _reserved_slots(self) -> float:
        n = max(1, len(self._ops))
        return self.budget.cpu_slots * self.reservation_frac / n

    def _shared_pool_free(self) -> float:
        shared = self.budget.cpu_slots * (1.0 - self.reservation_frac)
        borrowed = 0.0
        for st in self._ops.values():
            over = (st["inflight"] * st["cpu_per_task"]
                    - self._reserved_slots())
            if over > 0:
                borrowed += over
        return max(0.0, shared - borrowed)

    # -- accounting (executor hooks) -----------------------------------
    def on_launch(self, op) -> None:
        st = self._ops.get(id(op))
        if st is not None:
            st["inflight"] += 1

    def on_complete(self, op) -> None:
        st = self._ops.get(id(op))
        if st is not None and st["inflight"] > 0:
            st["inflight"] -= 1

    def on_bytes_acquired(self, nbytes: int) -> None:
        self.held_bytes += max(0, int(nbytes))
        self.peak_held_bytes = max(self.peak_held_bytes, self.held_bytes)

    def on_bytes_released(self, nbytes: int) -> None:
        self.held_bytes = max(0, self.held_bytes - max(0, int(nbytes)))

    def store_headroom(self) -> Optional[int]:
        """Bytes the execution may still acquire (None = unbounded).
        May go negative: block sizes are only known after they exist."""
        cap = self.budget.store_bytes
        if cap is None:
            return None
        return cap - self.held_bytes

    # -- the bound ------------------------------------------------------
    def max_inflight(self, op) -> int:
        st = self._ops.get(id(op))
        if st is None:
            return 10**9  # unregistered op: no reservation bound
        per_task = st["cpu_per_task"]
        own = self._reserved_slots() / per_task
        shared = self._shared_pool_free() / per_task
        bound = max(1, int(own + shared))
        headroom = self.store_headroom()
        if headroom is not None and headroom <= 0:
            # Over the store budget: drain mode. Shrink-only — never
            # below 1, so forward progress (and thus release of held
            # bytes) is always possible.
            return 1
        return bound

    def usage(self) -> Dict[str, Any]:
        return {
            "ops": {getattr(st["op"], "name", repr(st["op"])):
                    {"inflight": st["inflight"],
                     "cpu_per_task": st["cpu_per_task"]}
                    for st in self._ops.values()},
            "cpu_slots": self.budget.cpu_slots,
            "reserved_per_op": self._reserved_slots(),
            "shared_free": self._shared_pool_free(),
            "held_bytes": self.held_bytes,
            "peak_held_bytes": self.peak_held_bytes,
            "store_bytes": self.budget.store_bytes,
        }


# The manager for the currently-executing dataset plan (set by the
# streaming executor around a plan run; consulted by the policy below).
import contextvars as _contextvars

_current_rm: "_contextvars.ContextVar[Optional[ResourceManager]]" = \
    _contextvars.ContextVar("ray_tpu_data_rm", default=None)


def set_resource_manager(rm: Optional[ResourceManager]):
    return _current_rm.set(rm)


def current_resource_manager() -> Optional[ResourceManager]:
    return _current_rm.get()


class ReservationBackpressurePolicy(BackpressurePolicy):
    """Bound each op by its reservation in its execution's
    ResourceManager (reference: ReservationOpResourceAllocator
    max_task_output_bytes_to_read / can_submit gating). The manager is
    bound per-op at register_ops time; the contextvar is an explicit
    scoping hook for tests/embedders, not set by the executor."""

    name = "reservation"

    def max_inflight(self, op: Any) -> int:
        rm = (getattr(op, "_rt_resource_manager", None)
              or current_resource_manager())
        if rm is None:
            return 10**9
        return rm.max_inflight(op)


_BP_POLICIES.append(ReservationBackpressurePolicy())
