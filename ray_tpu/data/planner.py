"""Plan optimization rules + execution backpressure policies
(reference: python/ray/data/_internal/logical/optimizers.py — the
rule-based LogicalOptimizer/PhysicalOptimizer pair — and
_internal/execution/backpressure_policy/backpressure_policy.py).

Rules are pure plan→plan rewrites applied in order by the executor;
backpressure policies bound each operator's in-flight task window at
runtime. Both are extension points: `register_rule` /
`register_backpressure_policy` add custom ones process-wide, and a
Dataset can carry its own via `with_rules` (see dataset.py).
"""

from __future__ import annotations

from typing import Any, List

from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class Rule:
    """One plan rewrite (reference: logical/interfaces.Rule)."""

    name = "rule"

    def apply(self, plan: List[Any]) -> List[Any]:
        raise NotImplementedError


class OperatorFusionRule(Rule):
    """Fuse consecutive task-based map ops into one task (reference:
    _internal/logical/rules/operator_fusion.py). A map→map chain
    otherwise pays one dispatch + one object-store round trip per stage
    per block. Actor ops don't fuse (they pin state to a pool)."""

    name = "operator_fusion"

    def apply(self, plan: List[Any]) -> List[Any]:
        from ray_tpu.data.dataset import _MapBatches

        out: List[Any] = [plan[0]]
        for op in plan[1:]:
            prev = out[-1]
            if (isinstance(op, _MapBatches)
                    and isinstance(prev, _MapBatches)
                    and prev.num_cpus == op.num_cpus):
                stages = list(prev.fused_stages or [prev])
                fused = _MapBatches(
                    fn=None, batch_size=None, num_cpus=op.num_cpus,
                    window=min(prev.window, op.window),
                    name=f"{prev.name}->{op.name}")
                fused.fused_stages = stages + [op]
                out[-1] = fused
                continue
            out.append(op)
        return out


_RULES: List[Rule] = [OperatorFusionRule()]


def register_rule(rule: Rule) -> None:
    _RULES.append(rule)


def get_rules() -> List[Rule]:
    return list(_RULES)


def optimize(plan: List[Any], extra_rules: Any = None) -> List[Any]:
    for rule in list(_RULES) + list(extra_rules or []):
        try:
            plan = rule.apply(plan)
        except Exception:  # noqa: BLE001 - a broken custom rule must not
            logger.exception("plan rule %s failed; skipping", rule.name)
    return plan


# ---------------------------------------------------------------------------
# Backpressure policies
# ---------------------------------------------------------------------------
class BackpressurePolicy:
    """Bounds an operator's in-flight task window (reference:
    backpressure_policy.py — policies can only SHRINK concurrency)."""

    name = "backpressure"

    def max_inflight(self, op: Any) -> int:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """The operator's configured window (reference:
    concurrency_cap_backpressure_policy.py)."""

    name = "concurrency_cap"

    def max_inflight(self, op: Any) -> int:
        return max(1, getattr(op, "window", 4))


class ObjectStoreMemoryBackpressurePolicy(BackpressurePolicy):
    """Shrink windows while the local arena is under pressure: every
    in-flight block pins store space, and racing ahead of a full store
    just converts task throughput into spill churn (reference:
    streaming_output_backpressure / reservation policies)."""

    name = "object_store_memory"

    def __init__(self, high_watermark: float = 0.8):
        self.high_watermark = high_watermark

    def max_inflight(self, op: Any) -> int:
        window = max(1, getattr(op, "window", 4))
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is None:
                return window
            stats = w.shm.stats()
            frac = stats["bytes_in_use"] / max(1, stats["capacity"])
        except Exception:  # noqa: BLE001
            return window
        if frac >= self.high_watermark:
            return 1  # drain mode: one block in flight per operator
        return window


_BP_POLICIES: List[BackpressurePolicy] = [
    ConcurrencyCapBackpressurePolicy(),
    ObjectStoreMemoryBackpressurePolicy(),
]


def register_backpressure_policy(policy: BackpressurePolicy) -> None:
    _BP_POLICIES.append(policy)


def effective_window(op: Any) -> int:
    """The tightest bound across policies (policies only shrink)."""
    window = max(1, getattr(op, "window", 4))
    for policy in _BP_POLICIES:
        try:
            window = min(window, max(1, policy.max_inflight(op)))
        except Exception:  # noqa: BLE001
            continue
    return window
