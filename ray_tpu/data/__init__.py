"""ray_tpu.data — streaming datasets over the task/object plane.

Reference: python/ray/data (Dataset, read_api, DataIterator). See dataset.py
for the block/plan redesign notes (numpy-dict blocks) and _execution/ for
the op-DAG streaming executor all plans run on."""

from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.dataset import (
    Dataset,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004
    range_tensor,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data._execution import (
    recent_execution_summaries as execution_summaries,
)

__all__ = [
    "Block",
    "BlockMetadata",
    "DataIterator",
    "execution_summaries",
    "Dataset",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_csv",
    "read_json",
    "read_parquet",
    "read_text",
]

from ray_tpu._private.usage import record_library_usage as _rec

_rec("data")
del _rec
