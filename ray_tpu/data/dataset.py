"""Dataset: lazy logical plan + pull-based streaming execution over tasks.

Reference counterparts: python/ray/data/dataset.py:160 (`Dataset`),
_internal/execution/streaming_executor.py:52 (pull-based streaming executor
with backpressure), data/iterator.py (`iter_batches`, `streaming_split`).

Redesign notes (TPU-first, not a port):
- Blocks are numpy-dict columns (see block.py) — the zero-copy staging format
  for `jax.device_put`.
- The executor is a chain of async generators over ObjectRefs: each map op
  keeps a bounded submission window and yields results in order; pulling is
  lazy end-to-end, so backpressure needs no separate policy object — an
  unpulled downstream simply never advances upstream generators.
- Transforms run as ray_tpu tasks; block refs flow through the object store
  (shm, zero-copy on one node).
"""

from __future__ import annotations

import dataclasses
import builtins
import itertools
_range = builtins.range
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    BlockMetadata,
    VALUE_COL,
    block_concat,
    block_from_items,
    block_num_rows,
    block_select,
    block_slice,
    block_to_items,
    iter_block_batches,
    normalize_batch_output,
    as_arrow_block,
    as_numpy_block,
    as_pandas_batch,
    block_as_format,
    is_arrow_block,
)

DEFAULT_BLOCK_ROWS = 4096
DEFAULT_WINDOW = 4  # concurrent transform tasks per operator


# ---------------------------------------------------------------------------
# Logical ops
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class _Source:
    """Produces blocks driver-side, lazily."""

    make_blocks: Callable[[], Iterator[Block]]
    name: str = "Source"


@dataclasses.dataclass
class _RefSource:
    """Blocks already in the object store (materialized datasets), or a
    thunk producing their refs on first consumption (lazy all-to-all ops
    like hash_shuffle)."""

    refs: Any  # List[ObjectRef] | Callable[[], List[ObjectRef]]
    name: str = "RefSource"

    def resolve_refs(self) -> List[Any]:
        return self.refs() if callable(self.refs) else self.refs


@dataclasses.dataclass
class _MapBatches:
    fn: Optional[Callable]
    batch_size: Optional[int]
    num_cpus: float = 1.0
    window: int = DEFAULT_WINDOW
    name: str = "MapBatches"
    fn_kwargs: Optional[Dict[str, Any]] = None
    batch_format: Optional[str] = None  # None = numpy staging format
    # Set by _fuse_plan: a chain of map ops executed inside ONE task.
    fused_stages: Optional[List["_MapBatches"]] = None


@dataclasses.dataclass
class _MapBatchesActor:
    """Stateful transform: a pool of actors each holding one instance of
    `cls` (reference: ActorPoolMapOperator,
    _internal/execution/operators/actor_pool_map_operator.py). The expensive
    constructor (model load, engine init) runs once per actor, not per
    block."""

    cls: type
    batch_size: Optional[int]
    concurrency: int = 1
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    window_per_actor: int = 2
    name: str = "MapBatches(actors)"
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: Optional[Dict[str, Any]] = None
    fn_kwargs: Optional[Dict[str, Any]] = None
    batch_format: Optional[str] = None
    # Autoscaling ceiling: `concurrency` is the floor the pool starts
    # at, `max_concurrency` what the executor's PoolAutoscalerPolicy may
    # grow it to under sustained input-queue depth. None = fixed pool.
    max_concurrency: Optional[int] = None


def _apply_map_batches(op: _MapBatches, block: Block) -> Block:
    for stage in op.fused_stages or [op]:
        outs = []
        kwargs = stage.fn_kwargs or {}
        fmt = getattr(stage, "batch_format", None)
        for batch in iter_block_batches(block, stage.batch_size):
            outs.append(normalize_batch_output(
                stage.fn(block_as_format(batch, fmt), **kwargs)))
        block = block_concat(outs) if outs else {}
    return block


# ---------------------------------------------------------------------------
# Plan optimization
# ---------------------------------------------------------------------------
def _fuse_plan(plan: List[Any]) -> List[Any]:
    """Plan optimization now runs through the rule framework
    (data/planner.py — reference: _internal/logical/optimizers.py);
    operator fusion is its first built-in rule. Kept as the executor's
    entry point so custom rules registered via planner.register_rule
    apply to every dataset."""
    from ray_tpu.data.planner import optimize

    return optimize(plan)


# ---------------------------------------------------------------------------
# Streaming execution
# ---------------------------------------------------------------------------
def _exec_stream(plan: List[Any]) -> Iterator[Any]:
    """Plan → iterator of Block ObjectRefs.

    Default: the op-DAG streaming executor (data/_execution) — all
    operators run concurrently under the ExecutionBudget with
    output-queue-aware scheduling and actor-pool autoscaling. The
    legacy per-stage generator chain survives for one PR behind
    RAY_TPU_DATA_LEGACY_EXEC=1."""
    import os

    if os.environ.get("RAY_TPU_DATA_LEGACY_EXEC") == "1":
        return _exec_stream_legacy(plan)
    from ray_tpu.data._execution import execute_plan

    return execute_plan(plan)


def _exec_stream_legacy(plan: List[Any]) -> Iterator[Any]:
    """Plan → iterator of Block ObjectRefs (pull-based; bounded windows)."""
    plan = _fuse_plan(plan)
    src = plan[0]
    if isinstance(src, _RefSource):
        stream: Iterator[Any] = iter(src.resolve_refs())
    else:
        stream = (ray_tpu.put(b) for b in src.make_blocks())

    # Per-execution resource manager: reservation-based op budgets the
    # backpressure chain consults via the per-op binding register_ops
    # makes (planner.ReservationBackpressurePolicy; reference:
    # _internal/execution/resource_manager.py).
    from ray_tpu.data.planner import ResourceManager

    rm = ResourceManager()
    rm.register_ops(plan[1:])

    for op in plan[1:]:
        if isinstance(op, _MapBatchesActor):
            stream = _actor_map_stream(op, stream)
        else:
            stream = _map_stream(op, stream)
    return stream


def _map_stream(op: _MapBatches, upstream: Iterator[Any]) -> Iterator[Any]:
    from collections import deque

    @ray_tpu.remote
    def _run(block: Block, op=op) -> Block:
        return _apply_map_batches(op, block)

    from ray_tpu.data.planner import (
        current_resource_manager, effective_window,
    )

    remote = _run.options(num_cpus=op.num_cpus)
    rm = getattr(op, "_rt_resource_manager", None) or \
        current_resource_manager()
    inflight: "deque[Any]" = deque()
    for ref in upstream:
        inflight.append(remote.remote(ref))
        if rm is not None:
            rm.on_launch(op)
        # Backpressure policies re-evaluated per block: a full object
        # store shrinks the window to drain mode mid-stream; the
        # reservation policy bounds this op's share of execution CPU.
        if len(inflight) >= effective_window(op):
            if rm is not None:
                rm.on_complete(op)
            yield inflight.popleft()
    while inflight:
        if rm is not None:
            rm.on_complete(op)
        yield inflight.popleft()


def _actor_map_stream(op: _MapBatchesActor,
                      upstream: Iterator[Any]) -> Iterator[Any]:
    """Round-robin blocks over a pool of stateful actors, bounded in-flight
    per actor, yielding results in input order. Actors are torn down when the
    stream is exhausted (or abandoned)."""
    from collections import deque

    cls, batch_size, fn_kwargs = op.cls, op.batch_size, op.fn_kwargs or {}
    fmt = op.batch_format
    ctor_args = op.fn_constructor_args
    ctor_kwargs = op.fn_constructor_kwargs or {}

    @ray_tpu.remote
    class _BatchWorker:
        def __init__(self):
            self.inst = cls(*ctor_args, **ctor_kwargs)

        def run(self, block: Block) -> Block:
            outs = []
            for batch in iter_block_batches(block, batch_size):
                outs.append(normalize_batch_output(
                    self.inst(block_as_format(batch, fmt), **fn_kwargs)))
            return block_concat(outs) if outs else {}

    actor_cls = _BatchWorker.options(
        num_cpus=op.num_cpus, num_tpus=op.num_tpus)
    pool = [actor_cls.remote() for _ in _range(max(1, op.concurrency))]
    inflight: "deque[Any]" = deque()
    all_refs: List[Any] = []
    limit = max(1, op.window_per_actor) * len(pool)
    completed = False
    try:
        for i, ref in enumerate(upstream):
            out = pool[i % len(pool)].run.remote(ref)
            all_refs.append(out)
            inflight.append(out)
            if len(inflight) >= limit:
                yield inflight.popleft()
        while inflight:
            yield inflight.popleft()
        completed = True
    finally:
        if completed and all_refs:
            # Normal exhaustion: a downstream stage may still be consuming
            # the tail refs — don't kill the pool under running tasks.
            # (Abandoned stream: kill immediately; orphaned refs are never
            # consumed.) wait() is metadata-only, no payload pull.
            try:
                ray_tpu.wait(all_refs, num_returns=len(all_refs), timeout=120)
            except Exception:
                pass
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class Dataset:
    """Lazy dataset of columnar blocks (reference: data/dataset.py:160)."""

    def __init__(self, plan: List[Any]):
        self._plan = plan

    # -- transforms (lazy) ------------------------------------------------
    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    num_cpus: float = 1.0, num_tpus: float = 0.0,
                    concurrency: Any = DEFAULT_WINDOW,
                    batch_format: Optional[str] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[Dict[str, Any]] = None,
                    fn_kwargs: Optional[Dict[str, Any]] = None) -> "Dataset":
        """Function transforms run as tasks; a callable CLASS runs on a pool
        of `concurrency` stateful actors, constructed once each (reference:
        TaskPoolMapOperator vs ActorPoolMapOperator). For an actor class,
        ``concurrency=(min, max)`` enables autoscaling: the pool starts at
        `min` and the streaming executor grows it toward `max` on sustained
        input-queue depth, draining back (idle-first) when the queue
        empties. batch_format selects what `fn` sees: "numpy" (default;
        zero-copy views for Arrow-backed numeric columns), "pyarrow", or
        "pandas"."""
        max_concurrency: Optional[int] = None
        if isinstance(concurrency, (tuple, list)):
            if not isinstance(fn, type):
                raise ValueError(
                    "concurrency=(min, max) autoscaling requires a callable "
                    "class (actor pool); task-based map_batches takes an "
                    "int concurrency")
            lo, hi = concurrency
            if int(lo) < 1 or int(hi) < int(lo):
                raise ValueError(
                    f"bad concurrency range {concurrency!r}: need "
                    "1 <= min <= max")
            concurrency, max_concurrency = int(lo), int(hi)
        if isinstance(fn, type):
            return Dataset(self._plan + [_MapBatchesActor(
                fn, batch_size, concurrency=concurrency, num_cpus=num_cpus,
                num_tpus=num_tpus, name=f"MapBatches({fn.__name__})",
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs,
                fn_kwargs=fn_kwargs, batch_format=batch_format,
                max_concurrency=max_concurrency)])
        return Dataset(self._plan + [_MapBatches(
            fn, batch_size, num_cpus, concurrency,
            name=getattr(fn, "__name__", "map_batches"),
            fn_kwargs=fn_kwargs, batch_format=batch_format)])

    def map(self, fn: Callable, **opts) -> "Dataset":
        def _map_rows(batch: Block) -> Block:
            return block_from_items([fn(r) for r in block_to_items(batch)])

        return self.map_batches(_map_rows, **opts)

    def flat_map(self, fn: Callable, **opts) -> "Dataset":
        def _flat(batch: Block) -> Block:
            out: List[Any] = []
            for r in block_to_items(batch):
                out.extend(fn(r))
            return block_from_items(out)

        return self.map_batches(_flat, **opts)

    def filter(self, fn: Callable, **opts) -> "Dataset":
        def _filter(batch: Block) -> Block:
            mask = np.asarray([bool(fn(r)) for r in block_to_items(batch)])
            return block_select(batch, mask) if len(mask) else batch

        return self.map_batches(_filter, **opts)

    def add_column(self, name: str, fn: Callable, **opts) -> "Dataset":
        def _add(batch: Block) -> Block:
            out = dict(batch)
            out[name] = np.asarray(fn(batch))
            return out

        return self.map_batches(_add, **opts)

    def drop_columns(self, cols: Sequence[str], **opts) -> "Dataset":
        def _drop(batch: Block) -> Block:
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(_drop, **opts)

    def select_columns(self, cols: Sequence[str], **opts) -> "Dataset":
        def _select(batch: Block) -> Block:
            return {k: batch[k] for k in cols}

        return self.map_batches(_select, **opts)

    # -- consumption ------------------------------------------------------
    def iter_block_refs(self) -> Iterator[Any]:
        return _exec_stream(self._plan)

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self.iter_block_refs():
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     prefetch_batches: int = 1,
                     drop_last: bool = False,
                     batch_format: Optional[str] = "numpy"
                     ) -> Iterator[Block]:
        """Re-batched streaming iteration (reference: data/iterator.py).
        Arrow-backed blocks slice zero-copy; with the default
        batch_format="numpy", numeric null-free columns are yielded as
        zero-copy numpy views over the Arrow buffers."""
        for b in self._iter_batches_raw(batch_size=batch_size,
                                        drop_last=drop_last):
            yield block_as_format(b, batch_format)

    def _iter_batches_raw(self, *, batch_size: Optional[int],
                          drop_last: bool) -> Iterator[Block]:
        leftover: Optional[Block] = None
        for block in self.iter_blocks():
            if leftover is not None and block_num_rows(leftover):
                block = block_concat([leftover, block])
                leftover = None
            if batch_size is None:
                yield block
                continue
            n = block_num_rows(block)
            i = 0
            while n - i >= batch_size:
                yield block_slice(block, i, i + batch_size)
                i += batch_size
            if i < n:
                leftover = block_slice(block, i, n)
        if leftover is not None and block_num_rows(leftover) and not drop_last:
            yield leftover

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from block_to_items(block)

    def limit(self, n: int) -> "Dataset":
        """Lazy row-count truncation (stops pulling upstream once filled)."""
        parent = self

        def gen():
            remaining = n
            for block in parent.iter_blocks():
                if remaining <= 0:
                    return
                rows = block_num_rows(block)
                if rows <= remaining:
                    yield block
                    remaining -= rows
                else:
                    yield block_slice(block, 0, remaining)
                    return

        return Dataset([_Source(gen, name="Limit")])

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           drop_last: bool = False) -> Iterator[Dict[str, Any]]:
        """Batches as torch tensors (reference: iter_torch_batches)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            yield {k: torch.as_tensor(np.ascontiguousarray(v))
                   for k, v in batch.items()}

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        if isinstance(self._plan[0], _RefSource) and len(self._plan) == 1:
            return sum(ray_tpu.get(_remote_num_rows().remote(r))
                       for r in self._plan[0].resolve_refs())
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def schema(self) -> Optional[Dict[str, Any]]:
        for block in self.iter_blocks():
            return BlockMetadata.of(block).schema
        return None

    def materialize(self) -> "Dataset":
        refs = list(self.iter_block_refs())
        return Dataset([_RefSource(refs)])

    def num_blocks(self) -> int:
        return len(self.materialize()._plan[0].refs)

    # -- reorganization ---------------------------------------------------
    # All three exchange ops run as distributed map/reduce task DAGs: the
    # driver routes ObjectRefs and small metadata (row counts, key
    # samples), never block payloads (reference:
    # data/_internal/execution/operators/hash_shuffle.py,
    # planner/exchange/sort_task_spec.py). A one-block upstream keeps the
    # trivial local path.
    def repartition(self, num_blocks: int) -> "Dataset":
        """Split/merge exchange: input blocks are sliced at the global row
        boundaries of the target layout, slices route to merge tasks."""
        N = max(1, int(num_blocks))
        plan = list(self._plan)

        def run() -> List[Any]:
            upstream = list(_exec_stream(plan))

            @ray_tpu.remote
            def _count(b: Block) -> int:
                return block_num_rows(b)

            counts = ray_tpu.get([_count.remote(r) for r in upstream])
            total = sum(counts)
            per = -(-total // N) if total else 1

            @ray_tpu.remote
            def _slices(block: Block, bounds: List[Tuple[int, int]]):
                return tuple(block_slice(block, lo, hi)
                             for lo, hi in bounds)

            @ray_tpu.remote
            def _merge(*parts: Block) -> Block:
                nonempty = [p for p in parts if block_num_rows(p)]
                return block_concat(nonempty) if nonempty else {}

            out_parts: List[List[Any]] = [[] for _ in _range(N)]
            offset = 0
            for ref, cnt in zip(upstream, counts):
                bounds = []
                owners = []
                pos = 0
                while pos < cnt:
                    out_idx = min((offset + pos) // per, N - 1)
                    hi = min(cnt, (out_idx + 1) * per - offset)
                    bounds.append((pos, hi))
                    owners.append(out_idx)
                    pos = hi
                if not bounds:
                    continue
                if len(bounds) == 1:
                    out_parts[owners[0]].append(ref)
                else:
                    parts = _slices.options(
                        num_returns=len(bounds)).remote(ref, bounds)
                    for own, part in zip(owners, parts):
                        out_parts[own].append(part)
                offset += cnt
            return [_merge.remote(*parts) if parts else _merge.remote()
                    for parts in out_parts]

        return Dataset([_RefSource(run, name="Repartition")])

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Two-stage distributed shuffle: each block scatters its rows to
        P random partitions; each reduce merges and locally permutes —
        the composition is a uniform global shuffle with O(block) driver
        memory."""
        plan = list(self._plan)

        def run() -> List[Any]:
            upstream = list(_exec_stream(plan))
            P = len(upstream)
            if P <= 1:

                @ray_tpu.remote
                def _local_shuffle(b: Block, seed=seed) -> Block:
                    b = as_numpy_block(b)
                    n = block_num_rows(b)
                    perm = np.random.default_rng(seed).permutation(n)
                    return {k: np.asarray(v)[perm] for k, v in b.items()}

                return [_local_shuffle.remote(r) for r in upstream]

            @ray_tpu.remote
            def _scatter(block: Block, block_seed: int, P=P):
                block = as_numpy_block(block)
                rng = np.random.default_rng(block_seed)
                codes = rng.integers(0, P, block_num_rows(block))
                return tuple(
                    {k: np.asarray(v)[codes == p]
                     for k, v in block.items()}
                    for p in _range(P))

            @ray_tpu.remote
            def _merge_permute(part_seed: int, *parts: Block) -> Block:
                nonempty = [p for p in parts if block_num_rows(p)]
                merged = as_numpy_block(
                    block_concat(nonempty) if nonempty else {})
                n = block_num_rows(merged)
                perm = np.random.default_rng(part_seed).permutation(n)
                return {k: np.asarray(v)[perm] for k, v in merged.items()}

            root = np.random.default_rng(seed)
            seeds = [int(s) for s in
                     root.integers(0, 2**31 - 1, size=2 * P)]
            rows = [_scatter.options(num_returns=P).remote(u, seeds[i])
                    for i, u in enumerate(upstream)]
            return [_merge_permute.remote(seeds[P + p],
                                          *[row[p] for row in rows])
                    for p in _range(P)]

        return Dataset([_RefSource(run, name="RandomShuffle")])

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Distributed range-partition sort: sample key quantiles (the
        only data the driver touches), partition every block by the
        boundaries, sort each range locally. Output blocks are globally
        ordered."""
        plan = list(self._plan)

        def run() -> List[Any]:
            upstream = list(_exec_stream(plan))
            P = len(upstream)

            @ray_tpu.remote
            def _sort_block(b: Block, key=key,
                            descending=descending) -> Block:
                b = as_numpy_block(b)
                order = np.argsort(np.asarray(b[key]), kind="stable")
                if descending:
                    order = order[::-1]
                return {k: np.asarray(v)[order] for k, v in b.items()}

            if P <= 1:
                return [_sort_block.remote(r) for r in upstream]

            @ray_tpu.remote
            def _sample(b: Block, key=key, k: int = 64):
                b = as_numpy_block(b)
                vals = np.sort(np.asarray(b[key]))
                if len(vals) == 0:
                    return vals
                idx = np.linspace(0, len(vals) - 1,
                                  min(k, len(vals))).astype(np.int64)
                return vals[idx]

            samples = [s for s in
                       ray_tpu.get([_sample.remote(r) for r in upstream])
                       if len(s)]
            if not samples:
                return list(upstream)
            merged = np.sort(np.concatenate(samples))
            # P-1 interior boundaries at the sample quantiles.
            q = np.linspace(0, len(merged) - 1, P + 1)[1:-1]
            bounds = merged[q.astype(np.int64)]

            @ray_tpu.remote
            def _range_part(block: Block, key=key, bounds=bounds, P=P):
                block = as_numpy_block(block)
                codes = np.searchsorted(bounds, np.asarray(block[key]),
                                        side="right")
                return tuple(
                    {k: np.asarray(v)[codes == p]
                     for k, v in block.items()}
                    for p in _range(P))

            @ray_tpu.remote
            def _sort_merge(key: str, descending: bool,
                            *parts: Block) -> Block:
                nonempty = [p for p in parts if block_num_rows(p)]
                merged = as_numpy_block(
                    block_concat(nonempty) if nonempty else {})
                if not block_num_rows(merged):
                    return merged
                order = np.argsort(np.asarray(merged[key]), kind="stable")
                if descending:
                    order = order[::-1]
                return {k: np.asarray(v)[order] for k, v in merged.items()}

            rows = [_range_part.options(num_returns=P).remote(u)
                    for u in upstream]
            parts = [_sort_merge.remote(key, descending,
                                        *[row[p] for row in rows])
                     for p in _range(P)]
            # Ascending ranges; descending output reverses the range order
            # (each range is already internally descending).
            return parts[::-1] if descending else parts

        return Dataset([_RefSource(run, name="Sort")])

    def groupby(self, key: str, *,
                num_partitions: Optional[int] = None) -> "GroupedData":
        """num_partitions=None aggregates driver-side (right at single-host
        block counts); num_partitions=P runs a distributed hash shuffle
        (reference: _internal/execution/operators/hash_shuffle.py) so each
        of P reduce blocks holds COMPLETE groups — aggregations then run as
        per-block tasks with no driver materialization."""
        if num_partitions:
            return GroupedData(self.hash_shuffle(key, num_partitions), key,
                               pre_partitioned=True)
        return GroupedData(self, key)

    def hash_shuffle(self, key: str, num_partitions: int) -> "Dataset":
        """All-to-all: partition every block by a stable hash of `key`,
        merge partition p across blocks into one output block. Map and
        reduce are cluster tasks; the driver only routes refs (reference:
        hash shuffle map/reduce tasks, operators/hash_shuffle.py). Lazy
        like every other operator: the shuffle submits when the result is
        first consumed."""
        P = max(1, int(num_partitions))
        plan = list(self._plan)

        def run_shuffle() -> List[Any]:
            upstream = list(_exec_stream(plan))

            @ray_tpu.remote
            def _merge(*blocks: Block) -> Block:
                nonempty = [b for b in blocks if block_num_rows(b)]
                return block_concat(nonempty) if nonempty else {}

            if P == 1:
                # Degenerate shuffle: everything lands in one partition —
                # no map stage needed (num_returns=1 would hand _merge a
                # 1-tuple, not a block).
                return [_merge.remote(*upstream)]

            @ray_tpu.remote
            def _partition(block: Block, key=key, P=P):
                block = as_numpy_block(block)
                if not block or not block_num_rows(block):
                    # empty upstream block (e.g. a filter that dropped
                    # everything): every partition gets its empty schema
                    empty = {k: np.asarray(v)[:0] for k, v in block.items()}
                    return tuple(dict(empty) for _ in _range(P))
                vals = block[key]
                codes = _stable_hash_codes(vals, P)
                return tuple(
                    {k: np.asarray(v)[codes == p]
                     for k, v in block.items()}
                    for p in _range(P))

            rows = [_partition.options(num_returns=P).remote(u)
                    for u in upstream]
            return [_merge.remote(*[row[p] for row in rows])
                    for p in _range(P)]

        return Dataset([_RefSource(run_shuffle, name="HashShuffle")])

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: int = 8) -> "Dataset":
        """Distributed hash join (reference: _internal/execution/operators/
        join.py — hash-shuffle both sides by key, then per-partition joins).
        Both sides are partitioned with the same stable hash, so partition p
        of the left can only match partition p of the right; the P join
        tasks run cluster-side and the driver only routes refs — payload
        columns never materialize on the driver."""
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join how={how!r}")
        left = self.hash_shuffle(on, num_partitions)
        right = other.hash_shuffle(on, num_partitions)

        def run_join() -> List[Any]:
            lrefs = list(_exec_stream(list(left._plan)))
            rrefs = list(_exec_stream(list(right._plan)))

            @ray_tpu.remote
            def _schema(b: Block):
                import numpy as np
                b = as_numpy_block(b)
                return [(c, str(np.asarray(v).dtype)) for c, v in b.items()]

            # Schema hints (column name + dtype — no payload): an empty
            # partition on one side must still produce the full merged
            # schema WITH matching key dtypes, or pd.merge raises on e.g.
            # int64-vs-object key columns and downstream block_concat sees
            # inconsistent blocks.
            def side_schema(refs, other_refs):
                for sch in ray_tpu.get([_schema.remote(r) for r in refs]):
                    if sch:
                        return sch
                # Whole side empty: payload columns are unknowable, but the
                # key column must still merge cleanly — borrow its dtype
                # from the other side.
                for sch in ray_tpu.get(
                        [_schema.remote(r) for r in other_refs]):
                    for c, dt in sch:
                        if c == on:
                            return [(on, dt)]
                return [(on, "int64")]

            lsch = side_schema(lrefs, rrefs)
            rsch = side_schema(rrefs, lrefs)

            @ray_tpu.remote
            def _join_part(lb: Block, rb: Block, on=on, how=how,
                           lsch=tuple(lsch), rsch=tuple(rsch)) -> Block:
                import numpy as np
                import pandas as pd

                def frame(b, sch):
                    b = as_numpy_block(b)
                    if b:
                        return pd.DataFrame(dict(b))
                    return pd.DataFrame(
                        {c: np.empty(0, dtype=np.dtype(dt))
                         for c, dt in sch})

                out = frame(lb, lsch).merge(frame(rb, rsch), on=on, how=how)
                return {c: out[c].to_numpy() for c in out.columns}

            return [_join_part.remote(l, r)
                    for l, r in zip(lrefs, rrefs)]

        return Dataset([_RefSource(run_join, name=f"Join({how})")])

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two row-aligned datasets (reference:
        Dataset.zip). Right-side blocks are re-sliced to the left's block
        boundaries by cluster tasks; duplicate column names from the right
        get a "_1" suffix."""
        def run_zip() -> List[Any]:
            lrefs = list(_exec_stream(list(self._plan)))
            rrefs = list(_exec_stream(list(other._plan)))

            @ray_tpu.remote
            def _rows(b: Block) -> int:
                return block_num_rows(b)

            lcounts = ray_tpu.get([_rows.remote(r) for r in lrefs])
            rcounts = ray_tpu.get([_rows.remote(r) for r in rrefs])
            if sum(lcounts) != sum(rcounts):
                raise ValueError(
                    f"zip needs equal row counts; {sum(lcounts)} vs "
                    f"{sum(rcounts)}")

            # Right-block spans as (global_start, global_end, ref).
            spans = []
            pos = 0
            for ref, cnt in zip(rrefs, rcounts):
                spans.append((pos, pos + cnt, ref))
                pos += cnt

            @ray_tpu.remote
            def _zip_part(lb: Block, ranges, *rblocks) -> Block:
                lb = as_numpy_block(lb)
                parts = [block_slice(rb, lo, hi)
                         for rb, (lo, hi) in zip(rblocks, ranges)]
                nonempty = [p for p in parts if block_num_rows(p)]
                rb = as_numpy_block(
                    block_concat(nonempty) if nonempty else {})
                out = dict(lb)
                for k, v in rb.items():
                    out[k if k not in out else f"{k}_1"] = v
                return out

            out_refs = []
            pos = 0
            for lref, cnt in zip(lrefs, lcounts):
                lo, hi = pos, pos + cnt
                pos = hi
                needed = [(s, e, r) for s, e, r in spans
                          if e > lo and s < hi]
                ranges = [(max(lo, s) - s, min(hi, e) - s)
                          for s, e, _ in needed]
                out_refs.append(_zip_part.remote(
                    lref, ranges, *[r for _, _, r in needed]))
            return out_refs

        return Dataset([_RefSource(run_zip, name="Zip")])

    def split(self, n: int) -> List["Dataset"]:
        refs = list(self.iter_block_refs())
        out = []
        for i in _range(n):
            out.append(Dataset([_RefSource(refs[i::n])]))
        return out

    def union(self, *others: "Dataset") -> "Dataset":
        plans = [self._plan] + [o._plan for o in others]

        def gen(plans=plans):
            for p in plans:
                for ref in _exec_stream(p):
                    yield ray_tpu.get(ref)

        return Dataset([_Source(gen, name="Union")])

    # -- train integration ------------------------------------------------
    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """N coordinated iterators for N train workers (reference:
        data/iterator.py streaming_split + SplitCoordinator actor)."""
        from ray_tpu.data.iterator import DataIterator, _SplitCoordinator

        Coord = ray_tpu.remote(_SplitCoordinator)
        coord = Coord.options(num_cpus=0.5).remote(self._plan, n)
        return [DataIterator(coordinator=coord, split_idx=i)
                for i in _range(n)]

    def iterator(self) -> "DataIterator":
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(dataset=self)

    # -- write ------------------------------------------------------------
    def _write_parts(self, path: str, write_part: Callable) -> None:
        """Block-parallel write: one cluster task per block writes its own
        part file (reference: Data write ops run as tasks in the plan, not
        on the driver); the driver only routes refs and the final barrier
        returns row counts."""
        import os

        os.makedirs(path, exist_ok=True)

        @ray_tpu.remote
        def _w(block: Block, idx: int, path=path,
               write_part=write_part) -> int:
            write_part(block, idx, path)
            return block_num_rows(block)

        ray_tpu.get([_w.remote(ref, i)
                     for i, ref in enumerate(self.iter_block_refs())])

    def write_parquet(self, path: str) -> None:
        self._write_parts(path, _write_parquet_part)

    def write_json(self, path: str) -> None:
        """One JSONL file per block (reference: Dataset.write_json)."""
        self._write_parts(path, _write_json_part)

    def write_csv(self, path: str) -> None:
        self._write_parts(path, _write_csv_part)

    def to_pandas(self):
        """Materialize into one pandas DataFrame (driver memory)."""
        import pandas as pd

        blocks = list(self.iter_blocks())
        if not blocks:
            return pd.DataFrame()
        return pd.concat([as_pandas_batch(b) for b in blocks],
                         ignore_index=True)

    def stats(self) -> str:
        names = [getattr(op, "name", type(op).__name__) for op in self._plan]
        return " -> ".join(names)

    def __repr__(self) -> str:
        return f"Dataset(plan={self.stats()})"


def _write_parquet_part(block: Block, idx: int, path: str) -> None:
    import os

    import pyarrow.parquet as pq

    # Arrow blocks (e.g. straight from read_parquet/read_csv) write
    # directly — typed schemas (strings, nulls, nested lists) round-trip.
    table = as_arrow_block(block)
    pq.write_table(table, os.path.join(path, f"part-{idx:05d}.parquet"))


def _write_json_part(block: Block, idx: int, path: str) -> None:
    import json
    import os

    block = as_numpy_block(block)

    with open(os.path.join(path, f"part-{idx:05d}.jsonl"), "w") as f:
        for row in block_to_items(block):
            if not isinstance(row, dict):
                row = {VALUE_COL: row}
            f.write(json.dumps(
                {k: (v.tolist() if isinstance(v, np.ndarray)
                     else v.item() if isinstance(v, np.generic)
                     else v) for k, v in row.items()}) + "\n")


def _write_csv_part(block: Block, idx: int, path: str) -> None:
    import csv
    import os

    block = as_numpy_block(block)

    cols = list(block.keys())
    with open(os.path.join(path, f"part-{idx:05d}.csv"), "w",
              newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for j in _range(block_num_rows(block)):
            w.writerow([block[c][j] for c in cols])


def _stable_hash_codes(vals, P: int) -> np.ndarray:
    """Partition codes that are identical in EVERY worker process —
    builtin hash() is per-process seed-randomized and would scatter one
    key across partitions."""
    import zlib

    arr = np.asarray(vals)
    if arr.dtype.kind in "iub":
        return (arr.astype(np.int64) % P).astype(np.int64)
    return np.array(
        [zlib.crc32(repr(x).encode()) % P for x in arr], np.int64)


class GroupedData:
    """Groupby aggregations (reference: data/grouped_data.py). Driver-side
    composition by default; with pre_partitioned=True (hash_shuffle ran
    first, so every block holds complete groups) the aggregation itself is
    a per-block cluster task."""

    def __init__(self, ds: Dataset, key: str, pre_partitioned: bool = False):
        self._ds = ds
        self._key = key
        self._pre_partitioned = pre_partitioned

    def _gather(self):
        full = block_concat(list(self._ds.iter_blocks()))
        keys = np.asarray(full[self._key])
        uniq, inv = np.unique(keys, return_inverse=True)
        return full, uniq, inv

    def _agg(self, fn, cols: Optional[Sequence[str]], suffix: str) -> Dataset:
        if self._pre_partitioned:
            # Complete groups per block → aggregation is a per-block TASK.
            key = self._key

            def agg_block(block, key=key, fn=fn, cols=cols, suffix=suffix):
                if not block_num_rows(block):
                    return {}
                block = as_numpy_block(block)
                keys = np.asarray(block[key])
                uniq, inv = np.unique(keys, return_inverse=True)
                use = [c for c in (cols or block.keys()) if c != key]
                out = {key: uniq}
                for c in use:
                    vals = np.asarray(block[c])
                    # NB: _range — this module shadows builtin range with
                    # the Dataset factory.
                    out[f"{c}_{suffix}"] = np.asarray(
                        [fn(vals[inv == g]) for g in _range(len(uniq))])
                return out

            return Dataset(self._ds._plan + [_MapBatches(
                agg_block, batch_size=None, name=f"GroupAgg({suffix})")])
        full, uniq, inv = self._gather()
        cols = [c for c in (cols or full.keys()) if c != self._key]
        out: Dict[str, np.ndarray] = {self._key: uniq}
        for c in cols:
            vals = np.asarray(full[c])
            out[f"{c}_{suffix}"] = np.asarray(
                [fn(vals[inv == g]) for g in _range(len(uniq))])
        return from_items(block_to_items(out))

    def count(self) -> Dataset:
        if self._pre_partitioned:
            key = self._key

            def count_block(block, key=key):
                if not block_num_rows(block):
                    return {}
                block = as_numpy_block(block)
                keys = np.asarray(block[key])
                uniq, inv = np.unique(keys, return_inverse=True)
                return {key: uniq,
                        "count": np.bincount(inv, minlength=len(uniq))}

            return Dataset(self._ds._plan + [_MapBatches(
                count_block, batch_size=None, name="GroupCount")])
        full, uniq, inv = self._gather()
        counts = np.bincount(inv, minlength=len(uniq))
        return from_items(block_to_items(
            {self._key: uniq, "count": counts}))

    def sum(self, cols: Optional[Sequence[str]] = None) -> Dataset:
        return self._agg(np.sum, cols, "sum")

    def mean(self, cols: Optional[Sequence[str]] = None) -> Dataset:
        return self._agg(np.mean, cols, "mean")

    def min(self, cols: Optional[Sequence[str]] = None) -> Dataset:
        return self._agg(np.min, cols, "min")

    def max(self, cols: Optional[Sequence[str]] = None) -> Dataset:
        return self._agg(np.max, cols, "max")

    def map_groups(self, fn: Callable) -> Dataset:
        full, uniq, inv = self._gather()
        items: List[Any] = []
        for g in _range(len(uniq)):
            group = {k: v[inv == g] for k, v in full.items()}
            res = fn(group)
            if isinstance(res, list):
                items.extend(res)
            else:
                items.append(res)
        return from_items(items)


def _remote_num_rows():
    @ray_tpu.remote
    def _n(block: Block) -> int:
        return block_num_rows(block)

    return _n


# ---------------------------------------------------------------------------
# Read API (reference: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------
def from_items(items: Sequence[Any], *,
               block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    items = list(items)

    def gen():
        for i in _range(0, len(items), block_rows):
            yield block_from_items(items[i:i + block_rows])

    return Dataset([_Source(gen, name="FromItems")])


def range(n: int, *, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:  # noqa: A001
    def gen():
        for i in _range(0, n, block_rows):
            yield {"id": np.arange(i, min(i + block_rows, n))}

    return Dataset([_Source(gen, name="Range")])


def range_tensor(n: int, *, shape=(1,),
                 block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    def gen():
        for i in _range(0, n, block_rows):
            ids = np.arange(i, min(i + block_rows, n))
            data = np.broadcast_to(
                ids.reshape((-1,) + (1,) * len(shape)),
                (len(ids),) + tuple(shape)).copy()
            yield {"data": data}

    return Dataset([_Source(gen, name="RangeTensor")])


def from_numpy(arr: np.ndarray, *, column: str = "data",
               block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    def gen():
        for i in _range(0, len(arr), block_rows):
            yield {column: arr[i:i + block_rows]}

    return Dataset([_Source(gen, name="FromNumpy")])


def from_pandas(df) -> Dataset:
    def gen():
        yield {c: df[c].to_numpy() for c in df.columns}

    return Dataset([_Source(gen, name="FromPandas")])


def read_parquet(path: str) -> Dataset:
    """One block per parquet file (reference: read_api.py read_parquet)."""
    paths = _expand_paths(path, ".parquet")

    def gen():
        import pyarrow.parquet as pq

        for p in paths:
            # Arrow-native block: typed schema (strings, nulls, nested
            # lists) survives; numeric columns convert zero-copy at the
            # compute boundary (reference: _internal/arrow_block.py:194).
            yield pq.read_table(p)

    return Dataset([_Source(gen, name="ReadParquet")])


def read_csv(path: str) -> Dataset:
    """One Arrow block per csv file — columns come back TYPED (ints/floats
    inferred), not as strings (reference: read_api.py read_csv via
    pyarrow.csv)."""
    paths = _expand_paths(path, ".csv")

    def gen():
        from pyarrow import csv as pa_csv

        for p in paths:
            table = pa_csv.read_csv(p)
            if table.num_rows:
                yield table

    return Dataset([_Source(gen, name="ReadCSV")])


def _expand_paths(path: str, suffix: str) -> List[str]:
    import glob
    import os

    if os.path.isdir(path):
        # glob already returns dir-prefixed paths — no second join.
        return sorted(glob.glob(os.path.join(path, f"*{suffix}")))
    return sorted(glob.glob(path)) or [path]


def read_json(path: str, *, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    """JSONL file(s) → dataset, one or more blocks per file (reference:
    read_api.py read_json)."""
    paths = _expand_paths(path, ".jsonl")

    def gen():
        import json

        for p in paths:
            rows = []
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rows.append(json.loads(line))
                    if len(rows) >= block_rows:
                        yield block_from_items(rows)
                        rows = []
            if rows:
                yield block_from_items(rows)

    return Dataset([_Source(gen, name="ReadJSON")])


def read_text(path: str, *, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    """Text file(s) → one row per line, column "text" (reference:
    read_api.py read_text)."""
    paths = _expand_paths(path, ".txt")

    def gen():
        for p in paths:
            lines = []
            with open(p) as f:
                for line in f:
                    lines.append({"text": line.rstrip("\n")})
                    if len(lines) >= block_rows:
                        yield block_from_items(lines)
                        lines = []
            if lines:
                yield block_from_items(lines)

    return Dataset([_Source(gen, name="ReadText")])
