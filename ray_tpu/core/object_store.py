"""Object plane: per-node shared-memory store + per-worker memory store.

Counterparts in the reference:
- ``SharedMemoryStore`` ≙ plasma client (src/ray/object_manager/plasma/client.h:241)
  over the native arena in ray_tpu/native/shm_store.cc.
- ``MemoryStore`` ≙ the core worker's in-memory store for small/inlined objects
  (src/ray/core_worker/store_provider/memory_store/memory_store.h:45) — holds
  SerializedObjects and wakes blocked getters via asyncio events.

Serialized values are stored as: [u32 metadata_len][metadata][u32 nbufs]
([u64 buf_len][buf])* so multi-buffer zero-copy objects round-trip without an
extra concatenation copy on write.
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import struct
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject
from ray_tpu.exceptions import ObjectStoreFullError
from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SHM_OK = 0
SHM_ERR_EXISTS = -1
SHM_ERR_NOT_FOUND = -2
SHM_ERR_FULL = -3


def _arena_puts_counter():
    """Arena put outcomes — hit rate = hit / (hit + full). Lazy import:
    the metrics registry must not join this module's import chain (worker
    imports the store before the util package finishes initializing)."""
    from ray_tpu.util import metrics as um

    return um.get_counter(
        "ray_tpu_object_store_arena_puts_total",
        "Shared-memory arena put attempts by outcome (hit|full)",
        tag_keys=("result",))


def _spilled_objects_counter():
    from ray_tpu.util import metrics as um

    return um.get_counter("ray_tpu_object_store_spilled_objects_total",
                          "Objects spilled from the arena to disk")


def _spilled_bytes_counter():
    from ray_tpu.util import metrics as um

    return um.get_counter("ray_tpu_object_store_spilled_bytes_total",
                          "Bytes spilled from the arena to disk")


_PHASE_BOUNDARIES = (0.00001, 0.0001, 0.0005, 0.001, 0.005, 0.01,
                     0.05, 0.1, 0.5, 1.0)


def _put_phase_histogram():
    """Flight-recorder phase decomposition for large puts: alloc (arena
    reservation) / memcpy / seal — the profile the red
    `single_client_put_gigabytes` row needs."""
    from ray_tpu.util import metrics as um

    return um.get_histogram(
        "ray_tpu_object_store_put_phase_seconds",
        "Shared-memory put phases (alloc|memcpy|seal)",
        boundaries=_PHASE_BOUNDARIES, tag_keys=("phase",))


def _get_phase_histogram():
    """Per-ref get decomposition: lookup (index probe) / anchor (numpy
    view + release finalizer) / parse (header+buffer walk) — the per-ref
    cost profile behind `get_object_containing_10k_refs`."""
    from ray_tpu.util import metrics as um

    return um.get_histogram(
        "ray_tpu_object_store_get_phase_seconds",
        "Shared-memory get phases (lookup|anchor|parse)",
        boundaries=_PHASE_BOUNDARIES, tag_keys=("phase",))


def _load_native():
    from ray_tpu.native import build_library

    lib = ctypes.CDLL(build_library("shm_store"))
    lib.shm_store_create.restype = ctypes.c_void_p
    lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_store_open.restype = ctypes.c_void_p
    lib.shm_store_open.argtypes = [ctypes.c_char_p]
    lib.shm_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.shm_store_abort.restype = ctypes.c_int
    lib.shm_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_reclaim_stale.restype = ctypes.c_int
    lib.shm_store_reclaim_stale.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.shm_store_create_object.restype = ctypes.c_int
    lib.shm_store_create_object.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.shm_store_seal.restype = ctypes.c_int
    lib.shm_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_get.restype = ctypes.c_int
    lib.shm_store_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.shm_store_contains.restype = ctypes.c_int
    lib.shm_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_release.restype = ctypes.c_int
    lib.shm_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_delete.restype = ctypes.c_int
    lib.shm_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_base.restype = ctypes.c_void_p
    lib.shm_store_base.argtypes = [ctypes.c_void_p]
    lib.shm_store_map_size.restype = ctypes.c_uint64
    lib.shm_store_map_size.argtypes = [ctypes.c_void_p]
    lib.shm_store_bytes_in_use.restype = ctypes.c_uint64
    lib.shm_store_bytes_in_use.argtypes = [ctypes.c_void_p]
    lib.shm_store_capacity.restype = ctypes.c_uint64
    lib.shm_store_capacity.argtypes = [ctypes.c_void_p]
    lib.shm_store_num_objects.restype = ctypes.c_uint64
    lib.shm_store_num_objects.argtypes = [ctypes.c_void_p]
    lib.shm_store_prefault.restype = None
    lib.shm_store_prefault.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.shm_store_prefault_done.restype = ctypes.c_int
    lib.shm_store_prefault_done.argtypes = [ctypes.c_void_p]
    lib.shm_store_set_auto_evict.restype = None
    lib.shm_store_set_auto_evict.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.shm_store_lru_candidate.restype = ctypes.c_int
    lib.shm_store_lru_candidate.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_write.restype = None
    lib.shm_store_write.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_int,
    ]
    return lib


_native_lib = None
_native_lock = threading.Lock()


def native_lib():
    global _native_lib
    with _native_lock:
        if _native_lib is None:
            _native_lib = _load_native()
    return _native_lib


class SharedMemoryStore:
    """ctypes client of the native arena. Thread-safe (the native side locks)."""

    def __init__(self, path: str, capacity: Optional[int] = None,
                 create: bool = False, prefault: bool = True):
        self.path = path
        self._lib = native_lib()
        if create:
            assert capacity is not None
            self._handle = self._lib.shm_store_create(path.encode(), capacity)
        else:
            self._handle = self._lib.shm_store_open(path.encode())
        if not self._handle:
            raise OSError(f"failed to {'create' if create else 'open'} shm store {path}")
        # Background page prefault. The creator's MADV_POPULATE_WRITE
        # allocates the tmpfs pages once; other long-lived processes
        # (drivers) sweep too so their large puts hit populated PTEs. But
        # WORKERS skip it: a short-lived worker never amortizes a
        # full-arena PTE sweep (~0.3 s of one-core work per 2 GiB —
        # measured 8x slower 50-actor churn windows with per-worker
        # sweeps) and faults in lazily instead. Peer-arena READERS
        # (same-host cross-nodelet pulls) pass prefault=False: the pages
        # they touch are already resident in the owner's mapping.
        if prefault and (create
                         or not os.environ.get("RAY_TPU_WORKER_ID")):
            self._lib.shm_store_prefault(self._handle, 1 if create else 0)
        else:
            self._prefault_skipped = True
        base = self._lib.shm_store_base(self._handle)
        size = self._lib.shm_store_map_size(self._handle)
        self._base_addr = base
        self._view = (ctypes.c_char * size).from_address(base)
        self._mem = memoryview(self._view).cast("B")

    # -- raw bytes API --

    def put_raw(self, object_id: ObjectID, payload_parts: List[bytes]) -> bool:
        """Write an object as concatenated parts. False if it already exists.

        Flight-recorder phase stamps (alloc/memcpy/seal) are always-on for
        puts ≥1 MiB (3 perf_counter calls are noise against a memcpy that
        size) and sampled 1-in-N below it."""
        from ray_tpu._private import flight_recorder as _fr

        total = sum(len(p) for p in payload_parts)
        timed = _fr.enabled() and (total >= 1 << 20
                                   or _fr.maybe_sample())
        t0 = time.perf_counter() if timed else 0.0
        off = ctypes.c_uint64()
        rc = self._lib.shm_store_create_object(
            self._handle, object_id.binary(), total, ctypes.byref(off)
        )
        if rc == SHM_ERR_EXISTS:
            return False
        if rc == SHM_ERR_FULL:
            _arena_puts_counter().inc(tags={"result": "full"})
            raise ObjectStoreFullError(
                f"object of {total} bytes does not fit in store {self.path}"
            )
        if rc != SHM_OK:
            raise OSError(f"shm create failed rc={rc}")
        _arena_puts_counter().inc(tags={"result": "hit"})
        t1 = time.perf_counter() if timed else 0.0
        try:
            pos = off.value
            for part in payload_parts:
                n = len(part)
                if n >= 8 * 1024 * 1024:
                    # Parallel native copy for big buffers (memcpy is
                    # memory-bandwidth bound; one thread saturates ~5 GiB/s).
                    # numpy yields a pointer for readonly buffers too.
                    import numpy as _np

                    src_arr = _np.frombuffer(part, dtype=_np.uint8)
                    nthreads = min(8, os.cpu_count() or 1)
                    self._lib.shm_store_write(
                        self._handle, pos, src_arr.ctypes.data, n, nthreads)
                else:
                    src = bytes(part) if isinstance(part, memoryview) else part
                    ctypes.memmove(self._base_addr + pos, src, n)
                pos += n
        except BaseException:
            self._lib.shm_store_abort(self._handle, object_id.binary())
            raise
        t2 = time.perf_counter() if timed else 0.0
        self._lib.shm_store_seal(self._handle, object_id.binary())
        self._lib.shm_store_release(self._handle, object_id.binary())
        if timed:
            t3 = time.perf_counter()
            h = _put_phase_histogram()
            h.observe(t1 - t0, tags={"phase": "alloc"})
            h.observe(t2 - t1, tags={"phase": "memcpy"})
            h.observe(t3 - t2, tags={"phase": "seal"})
            if total >= 8 * 1024 * 1024:
                _fr.record_event(
                    "store_put", nbytes=total,
                    total_us=round((t3 - t0) * 1e6, 1),
                    alloc_us=round((t1 - t0) * 1e6, 1),
                    memcpy_us=round((t2 - t1) * 1e6, 1),
                    seal_us=round((t3 - t2) * 1e6, 1),
                    gib_per_s=round(
                        total / max(t2 - t1, 1e-9) / (1 << 30), 2))
        return True

    def get_raw(self, object_id: ObjectID) -> Optional[memoryview]:
        """Zero-copy view of a sealed object, or None. Caller must release()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.shm_store_get(
            self._handle, object_id.binary(), ctypes.byref(off), ctypes.byref(size)
        )
        if rc != SHM_OK:
            return None
        return self._mem[off.value : off.value + size.value]

    def release(self, object_id: ObjectID) -> None:
        if not self._handle:  # store closed; pin dies with the mapping
            return
        self._lib.shm_store_release(self._handle, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        return bool(self._lib.shm_store_contains(self._handle, object_id.binary()))

    def set_auto_evict(self, enabled: bool) -> None:
        self._lib.shm_store_set_auto_evict(self._handle, 1 if enabled else 0)

    def lru_candidate(self) -> Optional[ObjectID]:
        buf = ctypes.create_string_buffer(20)
        rc = self._lib.shm_store_lru_candidate(self._handle, buf)
        if rc != SHM_OK:
            return None
        return ObjectID(buf.raw)

    def delete(self, object_id: ObjectID) -> None:
        self._lib.shm_store_delete(self._handle, object_id.binary())

    # -- SerializedObject API --

    def put_serialized(self, object_id: ObjectID, obj: SerializedObject) -> bool:
        parts = [struct.pack(">I", len(obj.metadata)), obj.metadata,
                 struct.pack(">I", len(obj.buffers))]
        for buf in obj.buffers:
            parts.append(struct.pack(">Q", len(buf)))
            parts.append(buf)
        return self.put_raw(object_id, parts)

    def get_serialized(self, object_id: ObjectID) -> Optional[SerializedObject]:
        """Reconstruct a SerializedObject. Buffers are zero-copy memoryviews
        into the arena. The read pin is tied to the buffers' lifetime: when
        the last consumer (including numpy arrays deserialized zero-copy on
        top of them) is garbage-collected, the pin is released and the object
        becomes evictable — the plasma client's Buffer-release semantics
        (reference: plasma/client.h Release on buffer destruction)."""
        from ray_tpu._private import flight_recorder as _fr

        # Sampled phase stamps only: ref-heavy gets run this per ref
        # (10k-ref benches), so even cheap stamps must not be per-op.
        timed = _fr.enabled() and _fr.maybe_sample()
        t0 = time.perf_counter() if timed else 0.0
        view = self.get_raw(object_id)
        if view is None:
            return None
        t1 = time.perf_counter() if timed else 0.0
        import weakref

        import numpy as np

        # All handed-out buffers are views of `anchor`; its finalizer fires
        # once every consumer has dropped its reference.
        anchor = np.frombuffer(view, dtype=np.uint8)
        weakref.finalize(anchor, self.release, object_id)
        avm = memoryview(anchor)
        t2 = time.perf_counter() if timed else 0.0
        (mlen,) = struct.unpack(">I", view[:4])
        metadata = bytes(view[4 : 4 + mlen])
        pos = 4 + mlen
        (nbufs,) = struct.unpack(">I", view[pos : pos + 4])
        pos += 4
        buffers: List[memoryview] = []
        for _ in range(nbufs):
            (blen,) = struct.unpack(">Q", view[pos : pos + 8])
            pos += 8
            buffers.append(avm[pos : pos + blen])
            pos += blen
        if timed:
            h = _get_phase_histogram()
            h.observe(t1 - t0, tags={"phase": "lookup"})
            h.observe(t2 - t1, tags={"phase": "anchor"})
            h.observe(time.perf_counter() - t2, tags={"phase": "parse"})
        return SerializedObject(metadata, buffers, [])  # type: ignore[arg-type]

    def stats(self) -> Dict[str, int]:
        return {
            "capacity": self._lib.shm_store_capacity(self._handle),
            "bytes_in_use": self._lib.shm_store_bytes_in_use(self._handle),
            "num_objects": self._lib.shm_store_num_objects(self._handle),
        }

    def wait_prefault(self, timeout_s: float = 60.0) -> bool:
        """Block until the background page-population pass completes (used by
        benchmarks; ordinary operation never needs to wait). Clients skip
        the sweep entirely (see __init__) — nothing to wait for."""
        import time as _time

        if getattr(self, "_prefault_skipped", False):
            return True
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if self._lib.shm_store_prefault_done(self._handle):
                return True
            _time.sleep(0.05)
        return False

    def reclaim_stale(self, age_s: int = 60) -> int:
        """Reclaim orphaned in-progress creates from dead writers."""
        return self._lib.shm_store_reclaim_stale(self._handle, age_s)

    def close(self, unmap: bool = False) -> None:
        """Close the handle. By default the mapping stays alive until process
        exit because zero-copy views from get_raw may still be referenced;
        pass unmap=True only when no views can be outstanding."""
        if self._handle:
            if unmap:
                self._mem = None  # type: ignore[assignment]
                self._view = None  # type: ignore[assignment]
            self._lib.shm_store_close(self._handle, 1 if unmap else 0)
            self._handle = None


class MemoryStore:
    """Per-worker in-memory store for small objects and pending task returns.

    Async-first: getters await an asyncio.Event per object, mirroring the
    reference memory store's GetAsync callback chain.
    """

    class _Waiter:
        __slots__ = ("event", "count")

        def __init__(self):
            self.event = asyncio.Event()
            self.count = 0

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._objects: Dict[ObjectID, SerializedObject] = {}
        self._events: Dict[ObjectID, "MemoryStore._Waiter"] = {}
        self._thread_events: Dict[ObjectID, list] = {}
        self._lock = threading.Lock()

    def put(self, object_id: ObjectID, obj: SerializedObject) -> None:
        with self._lock:
            self._objects[object_id] = obj
            waiter = self._events.pop(object_id, None)
            tevents = self._thread_events.pop(object_id, None)
        if waiter is not None:
            self._loop.call_soon_threadsafe(waiter.event.set)
        if tevents:
            for ev in tevents:
                ev.set()

    def get_blocking(self, object_id: ObjectID,
                     timeout: Optional[float] = None
                     ) -> Optional[SerializedObject]:
        """Block the CALLING thread until the object arrives — no event-loop
        round trip. Used by the sync `ray.get` fast path: the completing
        reply callback sets a plain threading.Event, so the driver's main
        thread wakes directly (one futex) instead of via
        run_coroutine_threadsafe + Task + concurrent.Future (three wakes).
        Returns None on timeout."""
        ev = threading.Event()
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                return obj
            self._thread_events.setdefault(object_id, []).append(ev)
        try:
            if not ev.wait(timeout):
                return None
        finally:
            with self._lock:
                lst = self._thread_events.get(object_id)
                if lst is not None:
                    try:
                        lst.remove(ev)
                    except ValueError:
                        pass
                    if not lst:
                        del self._thread_events[object_id]
        with self._lock:
            return self._objects.get(object_id)

    def get_if_exists(self, object_id: ObjectID) -> Optional[SerializedObject]:
        with self._lock:
            return self._objects.get(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    async def get(self, object_id: ObjectID,
                  timeout: Optional[float] = None) -> SerializedObject:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                return obj
            waiter = self._events.get(object_id)
            if waiter is None:
                waiter = MemoryStore._Waiter()
                self._events[object_id] = waiter
            waiter.count += 1
        try:
            await asyncio.wait_for(waiter.event.wait(), timeout)
        finally:
            with self._lock:
                waiter.count -= 1
                if waiter.count == 0 and self._events.get(object_id) is waiter:
                    del self._events[object_id]
        with self._lock:
            obj = self._objects.get(object_id)
        if obj is None:
            from ray_tpu.exceptions import ObjectLostError

            raise ObjectLostError(f"object {object_id} deleted while waiting")
        return obj

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
        # Destroy outside the lock: a value holding ObjectRefs cascades into
        # ref-count callbacks that may re-enter this store.
        del obj

    def pop(self, object_id: ObjectID, default=None):
        """Remove and return the stored value (default when absent) — lets
        the owner's ref-zero path see WHAT it is deleting (inline value vs
        shm marker) and skip the arena/spill probes for inline objects.
        Pass a sentinel default to distinguish a stored None from absent
        (tasks returning None are common)."""
        with self._lock:
            return self._objects.pop(object_id, default)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)


# ---------------------------------------------------------------------------
# Spilling (reference: src/ray/raylet/local_object_manager.h + external
# storage). Redesign: overflow spilling — an object that does not fit the
# arena is written to a per-node spill directory in the same framed format;
# readers (worker materialize + nodelet fetch) fall back to it transparently.
# ---------------------------------------------------------------------------
def spill_path(spill_dir: str, object_id: ObjectID) -> str:
    return os.path.join(spill_dir, object_id.hex())


def spill_write(spill_dir: str, object_id: ObjectID,
                obj: SerializedObject) -> str:
    # Chaos seam: injected failure behaves exactly like a full/readonly
    # spill disk (the write-then-rename below guarantees no torn file).
    from ray_tpu._private.chaos import get_chaos

    get_chaos().failpoint("object_store.spill")
    os.makedirs(spill_dir, exist_ok=True)
    path = spill_path(spill_dir, object_id)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(struct.pack(">I", len(obj.metadata)))
        f.write(obj.metadata)
        f.write(struct.pack(">I", len(obj.buffers)))
        for buf in obj.buffers:
            f.write(struct.pack(">Q", len(buf)))
            f.write(buf)
    os.replace(tmp, path)
    _spilled_objects_counter().inc()
    _spilled_bytes_counter().inc(float(obj.total_bytes()))
    return path


def spill_read(spill_dir: str, object_id: ObjectID
               ) -> Optional[SerializedObject]:
    path = spill_path(spill_dir, object_id)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    (mlen,) = struct.unpack_from(">I", data, off); off += 4
    metadata = data[off:off + mlen]; off += mlen
    (nbuf,) = struct.unpack_from(">I", data, off); off += 4
    buffers = []
    for _ in range(nbuf):
        (blen,) = struct.unpack_from(">Q", data, off); off += 8
        buffers.append(data[off:off + blen]); off += blen
    return SerializedObject(bytes(metadata), buffers, [])


def spill_delete(spill_dir: str, object_id: ObjectID) -> None:
    try:
        os.remove(spill_path(spill_dir, object_id))
    except OSError:
        pass
