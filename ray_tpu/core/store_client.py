"""Pluggable GCS table storage (reference: src/ray/gcs/store_client/ —
store_client.h's AsyncPut/AsyncGetAll contract, redis_store_client.h for
the external-store head-node FT story, in_memory_store_client.h).

Two backends behind one interface:

- FileStoreClient — single atomic pickle snapshot (the round-2 behavior).
- SqliteStoreClient — one row per (table, key) in WAL-mode sqlite with
  content-digest change tracking: a save() writes ONLY mutated rows, so
  large stable tables (kv, actor registry) don't get rewritten every
  debounce tick the way a whole-snapshot pickle does.

The GCS keeps its debounced save loop; the backend decides how much IO a
save costs. Restart recovery reads everything back with load().
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Dict, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class StoreClient:
    """Table snapshot storage: save({table: rows}) / load() -> same."""

    def save(self, tables: Dict[str, Any]) -> None:
        raise NotImplementedError

    def load(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileStoreClient(StoreClient):
    """Atomic whole-snapshot pickle (tmp + rename)."""

    def __init__(self, path: str):
        self.path = path

    def save(self, tables: Dict[str, Any]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(tables, f, protocol=5)
        os.replace(tmp, self.path)

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except Exception:
            logger.exception("GCS snapshot unreadable; starting fresh")
            return None


class SqliteStoreClient(StoreClient):
    """Row-per-entry sqlite backend with incremental writes.

    Tables whose rows are dicts persist row-wise (key -> pickled value);
    scalar/list-valued tables persist as single rows under a reserved
    key. WAL mode keeps the GCS event loop's write stalls short; the
    digest cache means an unchanged row costs zero IO on save.
    """

    _SCALAR_KEY = "\x00scalar"

    def __init__(self, path: str):
        import sqlite3

        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._db = sqlite3.connect(path)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs (tbl TEXT, key TEXT, "
            "value BLOB, PRIMARY KEY (tbl, key))")
        self._db.commit()
        self._digests: Dict[tuple, bytes] = {}

    def save(self, tables: Dict[str, Any]) -> None:
        cur = self._db.cursor()
        seen = set()
        # Digest updates are STAGED and applied only after a successful
        # commit — recording them eagerly would mark rows clean that a
        # mid-save failure left uncommitted, and no later save would ever
        # retry them.
        staged: Dict[tuple, Optional[bytes]] = {}
        try:
            for tbl, rows in tables.items():
                if isinstance(rows, dict) and all(
                        isinstance(k, str) for k in rows):
                    items = rows.items()
                else:
                    items = [(self._SCALAR_KEY, rows)]
                for key, value in items:
                    blob = pickle.dumps(value, protocol=5)
                    digest = hashlib.blake2b(blob, digest_size=16).digest()
                    seen.add((tbl, key))
                    if self._digests.get((tbl, key)) == digest:
                        continue
                    cur.execute(
                        "INSERT OR REPLACE INTO gcs (tbl, key, value) "
                        "VALUES (?, ?, ?)", (tbl, key, blob))
                    staged[(tbl, key)] = digest
            # Deletions: rows we tracked that vanished from the tables.
            for (tbl, key) in list(self._digests):
                if (tbl, key) not in seen:
                    cur.execute("DELETE FROM gcs WHERE tbl=? AND key=?",
                                (tbl, key))
                    staged[(tbl, key)] = None
            if staged:
                self._db.commit()
        except Exception:
            try:
                self._db.rollback()
            except Exception:
                pass
            raise
        for key, digest in staged.items():
            if digest is None:
                self._digests.pop(key, None)
            else:
                self._digests[key] = digest

    def load(self) -> Optional[Dict[str, Any]]:
        cur = self._db.execute("SELECT tbl, key, value FROM gcs")
        out: Dict[str, Any] = {}
        any_rows = False
        for tbl, key, blob in cur:
            any_rows = True
            value = pickle.loads(blob)
            digest = hashlib.blake2b(blob, digest_size=16).digest()
            self._digests[(tbl, key)] = digest
            if key == self._SCALAR_KEY:
                out[tbl] = value
            else:
                out.setdefault(tbl, {})[key] = value
        return out if any_rows else None

    def close(self) -> None:
        try:
            self._db.commit()
            self._db.close()
        except Exception:
            pass


def create_store_client(path: Optional[str]) -> Optional[StoreClient]:
    """Backend selection by path: *.sqlite → SqliteStoreClient, anything
    else → FileStoreClient, None → no persistence."""
    if not path:
        return None
    if path.endswith(".sqlite") or path.endswith(".db"):
        return SqliteStoreClient(path)
    return FileStoreClient(path)
