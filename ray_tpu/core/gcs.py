"""GCS — the cluster control plane.

Counterpart of src/ray/gcs/gcs_server/ (C21–C23 in SURVEY.md §2.1): node
manager, actor manager (FSM with restarts), job manager, internal KV, function
store, placement groups, long-poll pub/sub, health checks, and the cluster
resource view. One asyncio process; tables in memory (a persistence hook mirrors
the reference's pluggable StoreClient so a Redis-style backend can slot in).

Redesign notes: the reference runs ~11 gRPC services on one asio loop; here one
RpcServer serves the union of handler methods. Actor scheduling leases workers
from nodelets exactly like normal-task scheduling does (reference:
gcs_actor_scheduler.h:115).
"""

from __future__ import annotations

import asyncio
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.backoff import Backoff, delay_for_attempt
from ray_tpu._private.chaos import get_chaos
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu._private.task_spec import ResourceSet
from ray_tpu.utils.config import get_config
from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# Pub/sub: long-poll channels (reference: src/ray/pubsub/, O(#subscribers)
# long-poll connections rather than O(#objects)).
# ---------------------------------------------------------------------------
class PubsubChannels:
    def __init__(self):
        self._messages: Dict[str, List[Tuple[int, Any]]] = {}
        self._seq: Dict[str, int] = {}
        self._cond = asyncio.Condition()
        self.max_backlog = 10_000

    async def publish(self, channel: str, message: Any) -> None:
        async with self._cond:
            seq = self._seq.get(channel, 0) + 1
            self._seq[channel] = seq
            backlog = self._messages.setdefault(channel, [])
            backlog.append((seq, message))
            if len(backlog) > self.max_backlog:
                del backlog[: len(backlog) // 2]
            self._cond.notify_all()

    async def poll(
        self, cursors: Dict[str, int], timeout: float = 30.0
    ) -> Dict[str, List[Tuple[int, Any]]]:
        """Return messages newer than each channel's cursor; blocks until
        something arrives or timeout."""
        deadline = time.monotonic() + timeout

        def _collect() -> Dict[str, List[Tuple[int, Any]]]:
            out: Dict[str, List[Tuple[int, Any]]] = {}
            for channel, cursor in cursors.items():
                if cursor > self._seq.get(channel, 0):
                    # Subscriber cursor from a previous GCS incarnation
                    # (sequences reset on restart): replay from the start.
                    cursor = 0
                msgs = [m for m in self._messages.get(channel, []) if m[0] > cursor]
                if msgs:
                    out[channel] = msgs
            return out

        async with self._cond:
            while True:
                out = _collect()
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                try:
                    await asyncio.wait_for(self._cond.wait(), remaining)
                except asyncio.TimeoutError:
                    return {}


# ---------------------------------------------------------------------------
# Tables
# ---------------------------------------------------------------------------
class NodeInfo:
    def __init__(self, node_id: NodeID, address: Tuple[str, int],
                 resources: Dict[str, float], object_store_path: str,
                 labels: Dict[str, str]):
        self.node_id = node_id
        self.address = address
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.object_store_path = object_store_path
        self.labels = labels
        self.alive = True
        self.last_heartbeat = time.monotonic()
        # unsatisfied lease shapes from the latest heartbeat (autoscaler
        # task-demand signal)
        self.demand: List[Dict[str, float]] = []


ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


class ActorInfo:
    def __init__(self, actor_id: ActorID, creation_spec: Any, name: str,
                 max_restarts: int, detached: bool):
        self.actor_id = actor_id
        self.creation_spec = creation_spec  # pickled TaskSpec bytes
        self.name = name
        self.max_restarts = max_restarts
        self.detached = detached
        self.state = ACTOR_PENDING
        self.address: Optional[Tuple[str, int]] = None
        self.node_id: Optional[NodeID] = None
        self.num_restarts = 0
        self.death_cause: str = ""

    def public_view(self) -> Dict[str, Any]:
        return {
            "actor_id": self.actor_id.hex(),
            "state": self.state,
            "name": self.name,
            "address": self.address,
            "node_id": self.node_id.hex() if self.node_id else None,
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
        }

    def to_state(self) -> Dict[str, Any]:
        return {
            "actor_id": self.actor_id.binary(),
            "creation_spec": self.creation_spec,
            "name": self.name,
            "max_restarts": self.max_restarts,
            "detached": self.detached,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id.binary() if self.node_id else None,
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
        }

    @staticmethod
    def from_state(state: Dict[str, Any]) -> "ActorInfo":
        info = ActorInfo(ActorID(state["actor_id"]), state["creation_spec"],
                         state["name"], state["max_restarts"],
                         state["detached"])
        info.state = state["state"]
        info.address = (tuple(state["address"])
                        if state["address"] else None)
        info.node_id = (NodeID(state["node_id"])
                        if state["node_id"] else None)
        info.num_restarts = state["num_restarts"]
        info.death_cause = state["death_cause"]
        return info


class PlacementGroupInfo:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"
        # bundle index -> node_id
        self.bundle_nodes: Dict[int, NodeID] = {}

    def to_state(self) -> Dict[str, Any]:
        return {
            "pg_id": self.pg_id.binary(),
            "bundles": self.bundles,
            "strategy": self.strategy,
            "name": self.name,
            "state": self.state,
            "bundle_nodes": {i: n.binary()
                             for i, n in self.bundle_nodes.items()},
        }

    @staticmethod
    def from_state(state: Dict[str, Any]) -> "PlacementGroupInfo":
        info = PlacementGroupInfo(
            PlacementGroupID(state["pg_id"]), state["bundles"],
            state["strategy"], state["name"])
        info.state = state["state"]
        info.bundle_nodes = {int(i): NodeID(n)
                             for i, n in state["bundle_nodes"].items()}
        return info


# ---------------------------------------------------------------------------
# The server
# ---------------------------------------------------------------------------
class GcsStorage:
    """Debounce layer over a pluggable StoreClient (reference:
    gcs/store_client/ — store_client.h contract, redis_store_client.h for
    external-store head-node FT). Backend by path: *.sqlite → row-wise
    incremental sqlite (WAL), anything else → atomic whole-snapshot
    pickle. Mutations mark dirty, a flush loop writes ≤1x per interval,
    shutdown flushes synchronously."""

    def __init__(self, path: Optional[str]):
        from ray_tpu.core.store_client import create_store_client

        self.path = path
        self.dirty = False
        try:
            self.client = create_store_client(path)
        except Exception:
            # A corrupt/garbage store file must not take down the control
            # plane it exists to protect: set it aside and start fresh
            # (same contract as an unreadable pickle snapshot).
            logger.exception("GCS store unusable; starting fresh")
            try:
                os.replace(path, path + ".corrupt")
                self.client = create_store_client(path)
            except Exception:
                self.client = None

    def load(self) -> Optional[Dict[str, Any]]:
        if self.client is None:
            return None
        try:
            return self.client.load()
        except Exception:
            logger.exception("GCS store unreadable; starting fresh")
            return None

    def save(self, tables: Dict[str, Any]) -> None:
        if self.client is None:
            return
        # Chaos seam: an injected failure here must leave dirty=True so
        # the flush loop retries (exactly the contract a full disk or a
        # killed store process exercises).
        get_chaos().failpoint("gcs.snapshot_save")
        self.client.save(tables)
        self.dirty = False


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None):
        self.server = RpcServer(host, port)
        self.pubsub = PubsubChannels()
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        # kill_actor arrivals for ids not registered yet (client-side
        # async actor creation): the late registration lands dead.
        # id -> tombstone time; TTL + size cap bound it (repeated kills of
        # bogus ids, or registrations that never arrive, must not grow it
        # forever). Insertion-ordered, so eviction drops the oldest.
        self._prekilled: Dict[ActorID, float] = {}
        self.named_actors: Dict[str, ActorID] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.kv: Dict[str, bytes] = {}
        self.jobs: Dict[int, Dict[str, Any]] = {}
        self._job_counter = 0
        self._nodelet_clients: Dict[NodeID, RpcClient] = {}
        self._background: List[asyncio.Task] = []
        self._actor_locks: Dict[ActorID, asyncio.Lock] = {}
        self._spread_rr = 0
        from collections import deque

        self.task_events: "deque" = deque(maxlen=20_000)
        # Last-write times of live metrics:* snapshots (hygiene scan input).
        self._metrics_seen: Dict[str, float] = {}
        self.storage = GcsStorage(persist_path)
        # Durable export-event files for external ingestion (reference:
        # src/ray/util/event.h + export_*.proto; gated by config).
        from ray_tpu._private.export_events import get_export_logger

        export_dir = (os.path.dirname(persist_path) if persist_path
                      else os.path.join("/tmp/ray_tpu", "default"))
        self.export = get_export_logger(export_dir)
        self._restore()

    def _export_event(self, source_type: str,
                      data: Dict[str, Any]) -> None:
        if self.export is not None:
            try:
                self.export.emit(source_type, data)
            except Exception:  # noqa: BLE001
                pass  # export is observability, never control flow

    def _restore(self) -> None:
        snap = self.storage.load()
        if not snap:
            return
        self.kv = snap.get("kv", {})
        self.jobs = {int(k): v for k, v in snap.get("jobs", {}).items()}
        self._job_counter = snap.get("job_counter", 0)
        self.named_actors = {n: ActorID(a)
                             for n, a in snap.get("named_actors", {}).items()}
        actors = snap.get("actors", [])
        # actors persist row-wise ({id_hex: state}) for incremental
        # backends; accept the old list form for pre-existing snapshots.
        states = actors.values() if isinstance(actors, dict) else actors
        for state in states:
            info = ActorInfo.from_state(state)
            self.actors[info.actor_id] = info
        for state in snap.get("placement_groups", {}).values():
            pg = PlacementGroupInfo.from_state(state)
            self.placement_groups[pg.pg_id] = pg
        logger.info("GCS restored %d actors, %d pgs, %d kv keys",
                    len(self.actors), len(self.placement_groups),
                    len(self.kv))

    def mark_dirty(self) -> None:
        self.storage.dirty = True

    def _snapshot_tables(self) -> Dict[str, Any]:
        return {
            # metrics:* snapshots are live telemetry from (possibly dead)
            # processes — persisting them would resurrect stale counters
            # after a GCS restart and inflate every merged total.
            "kv": {k: v for k, v in self.kv.items()
                   if not k.startswith("metrics:")},
            "jobs": {str(k): v for k, v in self.jobs.items()},
            "job_counter": self._job_counter,
            "named_actors": {n: a.binary()
                             for n, a in self.named_actors.items()},
            # row-wise so incremental backends rewrite only changed actors
            "actors": {a.actor_id.hex(): a.to_state()
                       for a in self.actors.values()},
            # committed PGs survive a GCS restart (reference: PGs live in
            # the Redis-backed store); nodelets re-report bundle holds via
            # heartbeat reconciliation either way.
            "placement_groups": {p.pg_id.hex(): p.to_state()
                                 for p in self.placement_groups.values()},
        }

    async def _persist_loop(self) -> None:
        while True:
            await asyncio.sleep(0.25)
            if self.storage.dirty:
                try:
                    self.storage.save(self._snapshot_tables())
                except Exception:
                    logger.exception("GCS snapshot failed")

    async def start(self) -> Tuple[str, int]:
        for name in dir(self):
            if name.startswith("rpc_"):
                self.server.register(name[4:], getattr(self, name))
        addr = await self.server.start()
        self._background.append(asyncio.ensure_future(self._health_check_loop()))
        self._background.append(asyncio.ensure_future(self._pg_retry_loop()))
        if self.storage.path:
            self._background.append(
                asyncio.ensure_future(self._persist_loop()))
        # Metrics: the GCS IS the KV store, so its registry flushes write
        # straight into the table (no RPC, no Worker). The write hops onto
        # the event loop — GCS tables are loop-thread-owned, and a direct
        # insert from the flusher thread would race _snapshot_tables'
        # iteration ("dictionary changed size during iteration").
        from ray_tpu.util import metrics as um

        loop = asyncio.get_running_loop()
        um.set_flush_sink(lambda key, payload: loop.call_soon_threadsafe(
            self._metrics_kv_put, key, payload))
        self._background.append(asyncio.ensure_future(self._metrics_loop()))
        # Flight recorder: lag-sample the GCS loop — a stalled GCS loop
        # delays every heartbeat/lease in the cluster, exactly the stall
        # the sampler exists to attribute.
        from ray_tpu._private import flight_recorder as _fr

        _fr.attach_loop(loop, "gcs")
        logger.info("GCS listening on %s:%d", *addr)
        return addr

    # Metric-snapshot hygiene (all on the loop thread). A process stale
    # for METRICS_TTL_S has its snapshot RETIRED: gauges drop (stale by
    # definition) while counters/histograms park under a per-origin
    # `metrics:_retired:<origin>` key — counters must stay monotonic in
    # /metrics, and keeping the parked copy per-origin means a process
    # that merely lost connectivity supersedes it on its next flush
    # instead of being double counted. Parked copies older than
    # METRICS_RETIRE_FOLD_S fold into one accumulator key to bound growth;
    # the fold gives up the supersede protection, so it waits a day — a
    # process that reconnects after a >24h partition (and somehow outlived
    # node health checks) may double count, a trade we accept to keep the
    # key space bounded on high-churn clusters.
    _RETIRED_PREFIX = "metrics:_retired:"
    _RETIRED_ACCUM_KEY = "metrics:_retired:_accum"
    METRICS_TTL_S = 600.0
    METRICS_RETIRE_FOLD_S = 86400.0

    def _metrics_kv_put(self, key: str, payload: bytes) -> None:
        """Loop-thread insert of a live metrics snapshot: stamps the
        last-write time (the TTL scan reads this instead of unpickling
        every snapshot every round) and supersedes any parked retired
        copy from the same origin."""
        self.kv[key] = payload
        self._metrics_seen[key] = time.time()
        rkey = self._RETIRED_PREFIX + key[len("metrics:"):]
        if self.kv.pop(rkey, None) is not None:
            self._metrics_seen.pop(rkey, None)

    async def _metrics_loop(self) -> None:
        import pickle as _pickle

        from ray_tpu.util import metrics as um

        g_nodes = um.get_gauge("ray_tpu_nodes_alive",
                               "Nodes currently registered and alive")
        g_actors = um.get_gauge("ray_tpu_actors_alive",
                                "Actors currently in the ALIVE state")
        g_tasks = um.get_gauge(
            "ray_tpu_task_events_stored",
            "Task events retained in the GCS ring buffer")
        while True:
            try:
                await asyncio.sleep(2.0)
                g_nodes.set(sum(1 for n in self.nodes.values() if n.alive))
                g_actors.set(sum(1 for a in self.actors.values()
                                 if a.state == "ALIVE"))
                g_tasks.set(float(len(self.task_events)))
                now = time.time()
                for key in [k for k in self.kv
                            if k.startswith("metrics:")
                            and not k.startswith(self._RETIRED_PREFIX)]:
                    seen = self._metrics_seen.get(key)
                    if seen is None:
                        # First sighting (e.g. written before this loop
                        # started): grace period begins now.
                        self._metrics_seen[key] = now
                        continue
                    if now - seen <= self.METRICS_TTL_S:
                        continue
                    try:
                        snaps = [s for s in _pickle.loads(bytes(self.kv[key]))
                                 if s.get("kind") in ("counter", "histogram")]
                    except Exception:
                        # Not a telemetry snapshot (foreign data under the
                        # metrics: prefix): never delete what we can't read
                        # — and re-stamp so we only retry once per TTL, not
                        # every 2s round.
                        self._metrics_seen[key] = now
                        continue
                    self.kv.pop(key, None)
                    self._metrics_seen.pop(key, None)
                    if snaps:
                        rkey = self._RETIRED_PREFIX + key[len("metrics:"):]
                        self.kv[rkey] = _pickle.dumps(snaps, protocol=5)
                        self._metrics_seen[rkey] = now
                # Fold long-retired parked copies into the accumulator.
                expired: List[Dict[str, Any]] = []
                for key in [k for k in self.kv
                            if k.startswith(self._RETIRED_PREFIX)
                            and k != self._RETIRED_ACCUM_KEY]:
                    seen = self._metrics_seen.setdefault(key, now)
                    if now - seen <= self.METRICS_RETIRE_FOLD_S:
                        continue
                    try:
                        expired.extend(_pickle.loads(bytes(self.kv[key])))
                    except Exception:
                        pass
                    self.kv.pop(key, None)
                    self._metrics_seen.pop(key, None)
                if expired:
                    merged: Dict[str, Any] = {}
                    fresh: Dict[Any, float] = {}
                    cur = self.kv.get(self._RETIRED_ACCUM_KEY)
                    if cur:
                        um.merge_snapshot(merged, fresh,
                                          _pickle.loads(bytes(cur)))
                    um.merge_snapshot(merged, fresh, expired)
                    self.kv[self._RETIRED_ACCUM_KEY] = _pickle.dumps(
                        [{"name": name, "kind": m["kind"],
                          "description": m["description"],
                          "values": m["values"], "ts": now}
                         for name, m in merged.items()], protocol=5)
            except asyncio.CancelledError:
                return
            except Exception:
                pass  # telemetry must never hurt the control plane

    async def stop(self) -> None:
        for t in self._background:
            t.cancel()
        for c in self._nodelet_clients.values():
            await c.close()
        if self.storage.path and self.storage.dirty:
            try:
                self.storage.save(self._snapshot_tables())
            except Exception:
                pass
        await self.server.stop()

    def _nodelet(self, node_id: NodeID) -> RpcClient:
        if node_id not in self._nodelet_clients:
            info = self.nodes[node_id]
            self._nodelet_clients[node_id] = RpcClient(*info.address, name="nodelet")
        return self._nodelet_clients[node_id]

    # ------------------------------------------------------------------
    # Node management (reference: gcs_node_manager.h:49)
    # ------------------------------------------------------------------
    async def rpc_register_node(
        self, node_id: bytes, address: Tuple[str, int],
        resources: Dict[str, float], object_store_path: str,
        labels: Optional[Dict[str, str]] = None,
    ) -> Dict[str, Any]:
        nid = NodeID(node_id)
        self.nodes[nid] = NodeInfo(nid, tuple(address), resources,
                                   object_store_path, labels or {})
        await self.pubsub.publish("nodes", {"event": "added", "node_id": node_id,
                                            "address": address})
        self._export_event("EXPORT_NODE", {
            "node_id": nid.hex(), "state": "ALIVE",
            "resources": resources, "labels": labels or {}})
        logger.info("node %s registered: %s", nid, resources)
        return {"ok": True}

    async def rpc_heartbeat(
        self, node_id: bytes, resources_available: Dict[str, float],
        load: Optional[Dict[str, Any]] = None,
        demand: Optional[List[Dict[str, float]]] = None,
        version: int = 0,
    ) -> Dict[str, Any]:
        nid = NodeID(node_id)
        info = self.nodes.get(nid)
        if info is None or not info.alive:
            # Unknown OR previously declared dead (e.g. a transient stall
            # exceeded the failure threshold): the node must re-register to
            # rejoin scheduling — its actors were already failed over.
            return {"ok": False, "reregister": True}
        info.last_heartbeat = time.monotonic()
        self._apply_resource_view(info, version, resources_available,
                                  demand or [])
        return {"ok": True}

    @staticmethod
    def _apply_resource_view(info, version: int,
                             resources_available: Dict[str, float],
                             demand: List[Dict[str, float]]) -> None:
        """Versioned apply (reference: ray_syncer's versioned snapshots,
        ray_syncer.h:40): an out-of-order sync or a heartbeat racing a
        fresher push must never roll the view back."""
        current = getattr(info, "resource_version", 0)
        if version < current:
            return
        info.resource_version = version
        info.resources_available = resources_available
        info.demand = demand

    async def rpc_sync_resources(
        self, node_id: bytes, version: int,
        resources_available: Dict[str, float],
        demand: Optional[List[Dict[str, float]]] = None,
    ) -> Dict[str, Any]:
        """Event-driven resource-view push (the ray_syncer analog): sent
        by nodelets within ~50 ms of an availability/demand change, so
        scheduling and autoscaling views are bounded by the debounce, not
        the heartbeat period."""
        info = self.nodes.get(NodeID(node_id))
        if info is None or not info.alive:
            return {"ok": False, "reregister": True}
        self._apply_resource_view(info, version, resources_available,
                                  demand or [])
        return {"ok": True}

    async def rpc_list_nodes(self) -> List[Dict[str, Any]]:
        return [
            {
                "node_id": n.node_id.binary(),
                "address": n.address,
                "alive": n.alive,
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
                "object_store_path": n.object_store_path,
                "labels": n.labels,
                "demand": n.demand,
            }
            for n in self.nodes.values()
        ]

    async def rpc_drain_node(self, node_id: bytes) -> Dict[str, Any]:
        nid = NodeID(node_id)
        info = self.nodes.get(nid)
        if info is None:
            return {"ok": False}
        await self._mark_node_dead(info, "drained")
        return {"ok": True}

    async def _health_check_loop(self) -> None:
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            deadline = cfg.heartbeat_interval_s * cfg.heartbeat_failure_threshold
            now = time.monotonic()
            for info in list(self.nodes.values()):
                if info.alive and now - info.last_heartbeat > deadline:
                    await self._mark_node_dead(info, "heartbeat timeout")

    async def _mark_node_dead(self, info: NodeInfo, reason: str) -> None:
        info.alive = False
        self._export_event("EXPORT_NODE", {
            "node_id": info.node_id.hex(), "state": "DEAD",
            "reason": reason})
        logger.warning("node %s dead: %s", info.node_id, reason)
        await self.pubsub.publish(
            "nodes", {"event": "removed", "node_id": info.node_id.binary(),
                      "reason": reason})
        # Fail over actors that lived on that node.
        for actor in list(self.actors.values()):
            if actor.node_id == info.node_id and actor.state == ACTOR_ALIVE:
                await self._on_actor_worker_death(actor, f"node died: {reason}")

    # ------------------------------------------------------------------
    # Internal KV + function store (reference: gcs_kv_manager.h,
    # gcs_function_manager.h)
    # ------------------------------------------------------------------
    async def rpc_kv_put(self, key: str, value: bytes,
                         overwrite: bool = True) -> bool:
        """Returns True iff the key already existed (write is skipped when
        overwrite=False), so first-writer-wins checks are a single RPC."""
        existed = key in self.kv
        if existed and not overwrite:
            return True
        # metrics:* snapshots arrive every ~2s from every process and are
        # excluded from the persisted snapshot — marking dirty for them
        # would rewrite an unchanged store to disk forever on idle clusters.
        if key.startswith("metrics:"):
            self._metrics_kv_put(key, value)
        else:
            self.kv[key] = value
            self.mark_dirty()
        return existed

    async def rpc_kv_cas(self, key: str, expect: Optional[bytes],
                         value: bytes) -> bool:
        """Atomic compare-and-swap (the GCS event loop serializes RPCs):
        writes `value` iff the current value is exactly `expect`
        (None = key absent). Lease-style leader claims build on this."""
        if self.kv.get(key) != expect:
            return False
        self.kv[key] = value
        self.mark_dirty()
        return True

    async def rpc_kv_get(self, key: str) -> Optional[bytes]:
        return self.kv.get(key)

    async def rpc_kv_del(self, key: str) -> bool:
        return self.kv.pop(key, None) is not None

    async def rpc_kv_keys(self, prefix: str = "") -> List[str]:
        return [k for k in self.kv if k.startswith(prefix)]

    # ------------------------------------------------------------------
    # Jobs (reference: gcs_job_manager.h:52)
    # ------------------------------------------------------------------
    async def rpc_add_job(self, metadata: Dict[str, Any]) -> int:
        self._job_counter += 1
        self.jobs[self._job_counter] = {
            "job_id": self._job_counter, "start_time": time.time(),
            "state": "RUNNING", **metadata,
        }
        self.mark_dirty()
        return self._job_counter

    async def rpc_finish_job(self, job_id: int) -> None:
        if job_id in self.jobs:
            self.jobs[job_id]["state"] = "FINISHED"
            self.jobs[job_id]["end_time"] = time.time()
            self._export_event("EXPORT_DRIVER_JOB", {
                "job_id": job_id, "state": "FINISHED"})
        # Non-detached actors of the job die with it.
        for actor in list(self.actors.values()):
            if (not actor.detached and actor.state != ACTOR_DEAD
                    and actor.actor_id.job_id().int() == job_id):
                await self._kill_actor(actor, "job finished", no_restart=True)

    async def rpc_list_jobs(self) -> List[Dict[str, Any]]:
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # Cluster resource view / scheduling hints (reference:
    # gcs_resource_manager.h + cluster_resource_scheduler)
    # ------------------------------------------------------------------
    def _alive_nodes(self) -> List[NodeInfo]:
        return [n for n in self.nodes.values() if n.alive]

    def _pick_node(self, resources: Dict[str, float],
                   strategy: str = "hybrid",
                   exclude: Optional[set] = None,
                   label_selector: Optional[Dict[str, str]] = None
                   ) -> Optional[NodeInfo]:
        """Composite policy (reference: composite_scheduling_policy.h:33 —
        feasibility filters then a placement score): label-selector and
        resource feasibility first (label_selector.h semantics via
        _private/labels.py), then hybrid pack-most-utilized
        (hybrid_scheduling_policy.h:50) or spread least-utilized."""
        from ray_tpu._private.labels import match_label_selector

        req = ResourceSet(resources)
        candidates = [
            n for n in self._alive_nodes()
            if (exclude is None or n.node_id not in exclude)
            and req.fits_in(n.resources_available)
            and match_label_selector(label_selector, n.labels)
        ]
        if not candidates:
            return None

        def utilization(n: NodeInfo) -> float:
            used = [
                1 - n.resources_available.get(k, 0) / v
                for k, v in n.resources_total.items() if v > 0
            ]
            return max(used) if used else 0.0

        if strategy == "spread":
            # Round-robin among the least-utilized candidates: a pure
            # utilization sort is deterministic between heartbeats, which
            # would send every pick in a burst to the same node.
            candidates.sort(key=lambda n: (utilization(n), n.node_id.hex()))
            self._spread_rr += 1
            return candidates[self._spread_rr % len(candidates)]
        return sorted(candidates, key=lambda n: (utilization(n), n.node_id.hex()),
                      reverse=True)[0]

    async def rpc_pick_node(
        self, resources: Dict[str, float], strategy: str = "hybrid",
        exclude: Optional[List[bytes]] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> Optional[Dict[str, Any]]:
        node = self._pick_node(
            resources, strategy,
            {NodeID(e) for e in exclude} if exclude else None,
            label_selector=label_selector)
        if node is None:
            return None
        return {"node_id": node.node_id.binary(), "address": node.address,
                "object_store_path": node.object_store_path}

    # ------------------------------------------------------------------
    # Actor management (reference: gcs_actor_manager.h:331 — the FSM)
    # ------------------------------------------------------------------
    def _actor_lock(self, actor_id: ActorID) -> asyncio.Lock:
        return self._actor_locks.setdefault(actor_id, asyncio.Lock())

    async def rpc_register_actor(
        self, actor_id: bytes, creation_spec: bytes, name: str = "",
        max_restarts: int = 0, detached: bool = False,
        get_if_exists: bool = False,
    ) -> Dict[str, Any]:
        aid = ActorID(actor_id)
        # Idempotent: a retried registration (client call_retrying after an
        # RPC blip) must not double-schedule or steal its own name
        # (reference: gcs_actor_manager.cc RegisterActor dedup).
        if aid in self.actors:
            return {"ok": True}
        if name:
            existing = self.named_actors.get(name)
            if existing is not None and existing != aid:
                if get_if_exists:
                    # Atomic get-or-create (reference: actor.py
                    # get_if_exists option → GetOrCreate in GCS).
                    return {"ok": True,
                            "existing_actor_id": existing.binary()}
                return {"ok": False,
                        "error": f"actor name {name!r} already taken"}
            self.named_actors[name] = aid
            self.mark_dirty()
        info = ActorInfo(aid, creation_spec, name, max_restarts, detached)
        self.actors[aid] = info
        self.mark_dirty()
        if self._prekilled.pop(aid, None) is not None:
            # A kill raced ahead of this (asynchronous) registration:
            # land the actor dead instead of scheduling a zombie.
            await self._actor_dead(info, "killed before registration")
            return {"ok": True}
        asyncio.ensure_future(self._schedule_actor(info))
        return {"ok": True}

    async def _schedule_actor(self, info: ActorInfo) -> None:
        async with self._actor_lock(info.actor_id):
            await self._schedule_actor_locked(info)

    async def _schedule_actor_locked(self, info: ActorInfo) -> None:
        import pickle

        from ray_tpu._private.task_spec import (NodeAffinityStrategy,
                                                PlacementGroupStrategy,
                                                SpreadStrategy)

        spec = pickle.loads(info.creation_spec)
        cfg = get_config()
        # Unified retry policy: full-jitter backoff de-synchronizes actor
        # scheduling herds (N restarting actors after a node death).
        # One clock for the whole scheduling budget: bo paces the retries
        # AND bounds them (bo.expired() is the terminal check).
        bo = Backoff(deadline=cfg.worker_start_timeout_s)
        strategy = spec.scheduling_strategy
        while info.state in (ACTOR_PENDING, ACTOR_RESTARTING):
            pg_bundle = None
            if isinstance(strategy, PlacementGroupStrategy):
                pgid = PlacementGroupID(strategy.placement_group_id)
                pg = self.placement_groups.get(pgid)
                bundle_idx = max(strategy.bundle_index, 0)
                nid = (pg.bundle_nodes.get(bundle_idx)
                       if pg is not None and pg.state == "CREATED" else None)
                node = self.nodes.get(nid) if nid is not None else None
                if node is not None and not node.alive:
                    node = None
                pg_bundle = (strategy.placement_group_id, bundle_idx)
            elif isinstance(strategy, NodeAffinityStrategy):
                nid = NodeID(bytes.fromhex(strategy.node_id))
                node = self.nodes.get(nid)
                if node is not None and not node.alive:
                    node = None
                if node is None and strategy.soft:
                    node = self._pick_node(spec.resources)
            elif isinstance(strategy, SpreadStrategy):
                node = self._pick_node(
                    spec.resources, strategy="spread",
                    label_selector=getattr(spec, "label_selector", None))
            else:
                node = self._pick_node(
                    spec.resources,
                    label_selector=getattr(spec, "label_selector", None))
            if node is None:
                if not await bo.sleep():
                    await self._actor_dead(
                        info, "no node with required resources "
                        f"{dict(spec.resources)}")
                    return
                continue
            try:
                lease = await self._nodelet(node.node_id).call(
                    "lease_worker",
                    resources=dict(spec.resources),
                    runtime_env=spec.runtime_env,
                    lifetime="actor",
                    pg_bundle=pg_bundle,
                    timeout=cfg.worker_start_timeout_s,
                )
                if not lease.get("ok"):
                    # Resources busy on the picked node: the actor stays
                    # pending (another lease may free them). Once the
                    # backoff deadline is exhausted sleep() returns False
                    # WITHOUT sleeping — keep pacing at the jittered cap
                    # (never in lockstep) instead of hot-spinning leases.
                    if not await bo.sleep():
                        await asyncio.sleep(
                            delay_for_attempt(64, maximum=bo.maximum))
                    continue
                worker_addr = tuple(lease["worker_address"])
                worker_client = RpcClient(*worker_addr, name="actor-worker")
                result = await worker_client.call(
                    "create_actor", creation_spec=info.creation_spec,
                    timeout=cfg.worker_start_timeout_s)
                await worker_client.close()
                if not result.get("ok"):
                    await self._actor_dead(
                        info, f"creation failed: {result.get('error')}")
                    return
                info.state = ACTOR_ALIVE
                self._export_event("EXPORT_ACTOR", {
                    "actor_id": info.actor_id.hex(), "state": "ALIVE",
                    "name": info.name,
                    "node_id": info.node_id.hex() if info.node_id
                    else None})
                self.mark_dirty()
                info.address = worker_addr
                info.node_id = node.node_id
                await self.pubsub.publish(
                    "actors", {"event": "alive",
                               "actor": info.public_view()})
                logger.info("actor %s alive at %s", info.actor_id, worker_addr)
                return
            except Exception as e:
                logger.warning("actor %s scheduling attempt failed: %r",
                               info.actor_id, e)
                if not await bo.sleep():
                    await self._actor_dead(info, f"scheduling failed: {e!r}")
                    return

    async def _actor_dead(self, info: ActorInfo, cause: str) -> None:
        info.state = ACTOR_DEAD
        self._export_event("EXPORT_ACTOR", {
            "actor_id": info.actor_id.hex(), "state": "DEAD",
            "name": info.name, "death_cause": cause})
        self.mark_dirty()
        info.death_cause = cause
        info.address = None
        if info.name:
            self.named_actors.pop(info.name, None)
        await self.pubsub.publish(
            "actors", {"event": "dead", "actor": info.public_view()})
        logger.info("actor %s dead: %s", info.actor_id, cause)

    async def _on_actor_worker_death(self, info: ActorInfo, cause: str) -> None:
        """FSM transition on worker failure (reference:
        gcs_actor_manager.cc:1318 RestartActor)."""
        async with self._actor_lock(info.actor_id):
            if info.state == ACTOR_DEAD:
                return
            if info.max_restarts == -1 or info.num_restarts < info.max_restarts:
                info.num_restarts += 1
                info.state = ACTOR_RESTARTING
                self.mark_dirty()
                info.address = None
                await self.pubsub.publish(
                    "actors", {"event": "restarting",
                               "actor": info.public_view()})
                logger.info("restarting actor %s (%d)", info.actor_id,
                            info.num_restarts)
                await self._schedule_actor_locked(info)
            else:
                await self._actor_dead(info, cause)

    async def rpc_report_worker_death(
        self, node_id: bytes, worker_address: Tuple[str, int], reason: str,
        actor_ids: Optional[List[bytes]] = None,
    ) -> None:
        addr = tuple(worker_address)
        for info in list(self.actors.values()):
            if info.state == ACTOR_ALIVE and info.address == addr:
                asyncio.ensure_future(
                    self._on_actor_worker_death(info, f"worker died: {reason}"))

    async def rpc_get_actor(self, actor_id: bytes) -> Optional[Dict[str, Any]]:
        info = self.actors.get(ActorID(actor_id))
        return info.public_view() if info else None

    async def rpc_get_named_actor(self, name: str) -> Optional[Dict[str, Any]]:
        aid = self.named_actors.get(name)
        if aid is None:
            return None
        return self.actors[aid].public_view()

    async def rpc_list_actors(self) -> List[Dict[str, Any]]:
        return [a.public_view() for a in self.actors.values()]

    # Tombstones older than this can't belong to an in-flight registration
    # (the register pipeline is bounded by worker_start_timeout_s + RPC
    # retries); the cap is a backstop against kill floods of bogus ids.
    PREKILL_TTL_S = 300.0
    PREKILL_MAX = 4096

    async def rpc_kill_actor(self, actor_id: bytes,
                             no_restart: bool = True) -> Dict[str, Any]:
        info = self.actors.get(ActorID(actor_id))
        if info is None:
            # Actor registration is asynchronous on the client: a kill can
            # legitimately arrive BEFORE register_actor. Tombstone the id
            # so the late registration lands dead instead of leaking a
            # zombie nobody holds a handle to.
            now = time.monotonic()
            self._prekilled.pop(ActorID(actor_id), None)  # refresh order
            self._prekilled[ActorID(actor_id)] = now
            for aid, ts in list(self._prekilled.items()):
                if (now - ts <= self.PREKILL_TTL_S
                        and len(self._prekilled) <= self.PREKILL_MAX):
                    break
                del self._prekilled[aid]
            return {"ok": False, "error": "no such actor"}
        # Reply as soon as the kill is ACCEPTED (reference: ray.kill is
        # asynchronous); the FSM transition + worker exit proceed on this
        # loop. A churn wave killing N actors then pays N cheap acks, not
        # N full teardowns.
        asyncio.ensure_future(
            self._kill_actor(info, "ray_tpu.kill", no_restart=no_restart))
        return {"ok": True}

    async def _kill_actor(self, info: ActorInfo, cause: str,
                          no_restart: bool) -> None:
        addr = info.address
        if no_restart:
            await self._actor_dead(info, cause)
        if addr is not None:
            try:
                client = RpcClient(*addr, name="kill")
                await client.call("exit_worker", timeout=5)
                await client.close()
            except Exception:
                pass  # worker may already be gone; nodelet reaps it

    # ------------------------------------------------------------------
    # Placement groups (reference: gcs_placement_group_mgr.h:232; 2-phase
    # prepare/commit via nodelets, bundle policies C15/C17)
    # ------------------------------------------------------------------
    async def rpc_create_placement_group(
        self, pg_id: bytes, bundles: List[Dict[str, float]], strategy: str,
        name: str = "",
    ) -> Dict[str, Any]:
        pgid = PlacementGroupID(pg_id)
        info = PlacementGroupInfo(pgid, bundles, strategy, name)
        self.placement_groups[pgid] = info
        self.mark_dirty()
        ok = await self._schedule_pg(info)
        if ok:
            info.state = "CREATED"
            self._export_event("EXPORT_PLACEMENT_GROUP", {
                "pg_id": info.pg_id.hex(), "state": "CREATED",
                "strategy": info.strategy})
            self.mark_dirty()
            await self.pubsub.publish("placement_groups",
                                      {"event": "created", "pg_id": pg_id})
            return {"ok": True,
                    "bundle_nodes": {i: nid.binary()
                                     for i, nid in info.bundle_nodes.items()}}
        # Stay PENDING: the retry loop re-schedules as the resource view
        # refreshes / nodes join (reference: GcsPlacementGroupManager retry
        # queue). Permanent infeasibility is indistinguishable from "not yet".
        return {"ok": False, "error": "placement group pending", "retry": True}

    async def _pg_retry_loop(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            for info in list(self.placement_groups.values()):
                if info.state != "PENDING":
                    continue
                try:
                    # _schedule_pg itself handles the removed-while-
                    # scheduling race (membership check + bundle return).
                    if await self._schedule_pg(info):
                        info.state = "CREATED"
                        self._export_event("EXPORT_PLACEMENT_GROUP", {
                            "pg_id": info.pg_id.hex(), "state": "CREATED",
                            "strategy": info.strategy})
                        self.mark_dirty()
                        await self.pubsub.publish(
                            "placement_groups",
                            {"event": "created",
                             "pg_id": info.pg_id.binary()})
                except Exception as e:
                    logger.warning("pg retry failed: %r", e)

    async def _schedule_pg(self, info: PlacementGroupInfo) -> bool:
        # Choose nodes per bundle under the strategy.
        sim_avail = {
            n.node_id: dict(n.resources_available) for n in self._alive_nodes()
        }
        assignment: Dict[int, NodeID] = {}
        used_nodes: set = set()
        for i, bundle in enumerate(info.bundles):
            req = ResourceSet(bundle)
            candidates = [
                nid for nid, avail in sim_avail.items() if req.fits_in(avail)
            ]
            if info.strategy in ("STRICT_PACK", "PACK") and assignment:
                pref = [nid for nid in candidates if nid in used_nodes]
                if pref:
                    candidates = pref
                elif info.strategy == "STRICT_PACK":
                    return False
            if info.strategy == "STRICT_SPREAD":
                candidates = [nid for nid in candidates if nid not in used_nodes]
            elif info.strategy == "SPREAD":
                fresh = [nid for nid in candidates if nid not in used_nodes]
                if fresh:
                    candidates = fresh
            if not candidates:
                return False
            nid = candidates[0]
            req.subtract_from(sim_avail[nid])
            assignment[i] = nid
            used_nodes.add(nid)
        # 2-phase: prepare all, then commit (reference:
        # placement_group_resource_manager.h:50).
        prepared: List[Tuple[NodeID, int]] = []
        try:
            for i, nid in assignment.items():
                r = await self._nodelet(nid).call(
                    "prepare_bundle", pg_id=info.pg_id.binary(),
                    bundle_index=i, resources=info.bundles[i])
                if not r.get("ok"):
                    raise RuntimeError("prepare failed")
                prepared.append((nid, i))
            for i, nid in assignment.items():
                await self._nodelet(nid).call(
                    "commit_bundle", pg_id=info.pg_id.binary(), bundle_index=i)
        except Exception as e:
            logger.warning("pg %s scheduling failed: %r", info.pg_id, e)
            for nid, i in prepared:
                try:
                    await self._nodelet(nid).call(
                        "return_bundle", pg_id=info.pg_id.binary(),
                        bundle_index=i)
                except Exception:
                    pass
            return False
        info.bundle_nodes = assignment
        if self.placement_groups.get(info.pg_id) is not info:
            # Removed while we were preparing/committing (the retry loop
            # races rpc_remove_placement_group): give the bundles back
            # immediately or they leak on the nodelets forever.
            for i, nid in assignment.items():
                try:
                    await self._nodelet(nid).call(
                        "return_bundle", pg_id=info.pg_id.binary(),
                        bundle_index=i)
                except Exception:
                    pass
            return False
        return True

    async def rpc_remove_placement_group(self, pg_id: bytes) -> Dict[str, Any]:
        pgid = PlacementGroupID(pg_id)
        info = self.placement_groups.pop(pgid, None)
        if info is None:
            return {"ok": False}
        info.state = "REMOVED"  # in-flight retry scheduling must not revive it
        self._export_event("EXPORT_PLACEMENT_GROUP", {
            "pg_id": info.pg_id.hex(), "state": "REMOVED"})
        self.mark_dirty()
        for i, nid in info.bundle_nodes.items():
            try:
                await self._nodelet(nid).call(
                    "return_bundle", pg_id=pg_id, bundle_index=i)
            except Exception:
                pass
        return {"ok": True}

    async def rpc_get_placement_group(self, pg_id: bytes) -> Optional[Dict[str, Any]]:
        info = self.placement_groups.get(PlacementGroupID(pg_id))
        if info is None:
            return None
        return {"pg_id": pg_id, "state": info.state, "strategy": info.strategy,
                "bundles": info.bundles,
                "bundle_nodes": {i: n.binary()
                                 for i, n in info.bundle_nodes.items()}}

    async def rpc_list_placement_groups(self) -> List[Dict[str, Any]]:
        return [
            {"pg_id": p.pg_id.binary(), "state": p.state, "name": p.name,
             "strategy": p.strategy, "bundles": p.bundles}
            for p in self.placement_groups.values()
        ]

    # ------------------------------------------------------------------
    # Pub/sub RPC surface
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # Task events (reference: gcs_task_manager.h:94 — bounded aggregation
    # feeding the state API and `timeline`)
    # ------------------------------------------------------------------
    async def rpc_report_task_events(
            self, events: List[Dict[str, Any]]) -> None:
        self.task_events.extend(events)
        if self.export is not None:
            try:
                self.export.emit_many("EXPORT_TASK", events)
            except Exception:  # noqa: BLE001
                pass  # export is observability, never control flow

    async def rpc_list_task_events(
            self, limit: int = 1000) -> List[Dict[str, Any]]:
        return list(self.task_events)[-limit:]

    async def rpc_pubsub_poll(
        self, cursors: Dict[str, int], timeout: float = 30.0
    ) -> Dict[str, List[Tuple[int, Any]]]:
        return await self.pubsub.poll(cursors, timeout)

    async def rpc_publish(self, channel: str, message: Any) -> None:
        await self.pubsub.publish(channel, message)

    async def rpc_pubsub_seq(self, channel: str) -> int:
        """Current sequence number of a channel — lets a new subscriber
        start from "now" instead of replaying the retained backlog."""
        return self.pubsub._seq.get(channel, 0)

    async def rpc_ping(self) -> str:
        return "pong"


async def run_gcs_server(host: str, port: int,
                         persist_path: Optional[str] = None) -> GcsServer:
    gcs = GcsServer(host, port, persist_path=persist_path)
    await gcs.start()
    return gcs


def main() -> None:  # pragma: no cover - exercised via subprocess
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--persist-path", default=None)
    args = parser.parse_args()

    async def _run():
        await run_gcs_server(args.host, args.port,
                             persist_path=args.persist_path)
        await asyncio.Event().wait()

    asyncio.run(_run())


if __name__ == "__main__":
    main()
