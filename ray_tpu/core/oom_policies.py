"""Pluggable OOM worker-killing policies (reference:
src/ray/raylet/worker_killing_policy.h:69 RetriableLIFOWorkerKillingPolicy
+ worker_killing_policy_group_by_owner.h — the set C19 in SURVEY §2.1).

A policy picks the victim among LEASED, live workers when the node
crosses the memory threshold. Selection invariants shared by all
policies: task workers before actor workers (a killed task retries;
actor state is harder to recover), and the chosen worker is returned to
the monitor loop which kills + reaps it.

Select with config `oom_killer_policy`:
  "retriable_lifo"  (default) most recently leased task worker first
  "group_by_owner"  kill from the submitter with the MOST leased workers
                    (newest first) — the biggest offender pays, lone
                    submitters are spared as long as possible
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Type


class WorkerKillingPolicy:
    name = "base"

    def select(self, leased_workers: List[Any]) -> Optional[Any]:
        raise NotImplementedError


class RetriableLIFOPolicy(WorkerKillingPolicy):
    """Most recently leased task worker first (reference:
    worker_killing_policy.h:69): the newest work has the least sunk cost
    and its retry is cheapest."""

    name = "retriable_lifo"

    def select(self, leased_workers: List[Any]) -> Optional[Any]:
        if not leased_workers:
            return None
        ordered = sorted(
            leased_workers,
            key=lambda w: (w.lifetime != "task", -w.last_idle))
        return ordered[0]


class GroupByOwnerPolicy(WorkerKillingPolicy):
    """Group task workers by the submitter that leased them; kill the
    newest worker of the LARGEST group (reference:
    worker_killing_policy_group_by_owner.h — the runaway fan-out pays
    before well-behaved submitters lose anything)."""

    name = "group_by_owner"

    def select(self, leased_workers: List[Any]) -> Optional[Any]:
        tasks = [w for w in leased_workers if w.lifetime == "task"]
        pool = tasks or leased_workers
        if not pool:
            return None
        groups: Dict[Any, List[Any]] = {}
        for w in pool:
            groups.setdefault(getattr(w, "lease_owner", None), []).append(w)
        biggest = max(groups.values(),
                      key=lambda ws: (len(ws), max(w.last_idle
                                                   for w in ws)))
        return max(biggest, key=lambda w: w.last_idle)


_POLICIES: Dict[str, Type[WorkerKillingPolicy]] = {
    RetriableLIFOPolicy.name: RetriableLIFOPolicy,
    GroupByOwnerPolicy.name: GroupByOwnerPolicy,
}


def register_policy(cls: Type[WorkerKillingPolicy]) -> None:
    """Third-party policies plug in by name (the pluggable half of C19)."""
    _POLICIES[cls.name] = cls


def get_policy(name: str) -> WorkerKillingPolicy:
    cls = _POLICIES.get(name)
    if cls is None:
        raise ValueError(
            f"unknown oom_killer_policy {name!r}; known: "
            f"{sorted(_POLICIES)}")
    return cls()
