"""Nodelet — the per-node manager (raylet equivalent, SURVEY §2.1 C13–C20).

Owns: the node's shared-memory object store file, the worker pool (spawning /
reaping worker processes), local resource accounting + the lease protocol,
placement-group bundle prepare/commit, and heartbeats to GCS.

Redesign vs the reference raylet: no separate plasma server process (the store
is the mapped arena from shm_store.cc); leases are granted over the same RPC
plane; worker pushes happen directly submitter→worker so the nodelet stays off
the task hot path entirely (the reference also bypasses the raylet for actor
calls, but normal tasks flow through its dispatch queue — here a lease is a
worker address and the submitter talks to the worker directly, which is why
task throughput scales with submitters, not with the nodelet).
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket as socket_mod
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.chaos import get_chaos
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu._private.task_spec import ResourceSet
from ray_tpu.core.object_store import SharedMemoryStore
from ray_tpu.util import metrics as um
from ray_tpu.utils.config import get_config
from ray_tpu.utils.logging import get_logger

logger = get_logger(__name__)


# Lease-path metric definitions — one site per metric (the registry dedupes
# by name; a second inline definition would silently drift).
def _m_leases_granted() -> "um.Counter":
    return um.get_counter("ray_tpu_leases_granted_total",
                          "Worker leases granted by this nodelet",
                          tag_keys=("node",))


def _m_leases_queued() -> "um.Counter":
    return um.get_counter("ray_tpu_leases_queued_total",
                          "Lease requests that had to wait for resources",
                          tag_keys=("node",))


def _m_sched_latency() -> "um.Histogram":
    return um.get_histogram(
        "ray_tpu_scheduling_latency_seconds",
        "Lease request arrival -> worker grant on this nodelet",
        tag_keys=("node",))


def _sweep_dead_arenas(shm_dir: str = "/dev/shm") -> int:
    """Unlink ray_tpu arenas whose owning nodelet is dead (a SIGKILL'd run
    leaks its arena with the full capacity committed — MADV_POPULATE pages).
    Ownership = sidecar <arena>.pid; no sidecar + old mtime = pre-crash
    leftover. Returns the number of arenas reclaimed."""
    reclaimed = 0
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return 0
    now = time.time()
    for name in names:
        if not name.startswith("ray_tpu_") or name.endswith(".pid"):
            continue
        arena = os.path.join(shm_dir, name)
        pid_file = arena + ".pid"
        dead = False
        try:
            with open(pid_file) as f:
                pid = int(f.read().strip())
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                dead = True
            except PermissionError:
                pass  # alive, other user
        except (OSError, ValueError):
            # No/garbled sidecar: reclaim only if clearly stale.
            try:
                dead = now - os.path.getmtime(arena) > 300
            except OSError:
                continue
        if dead:
            for p in (arena, pid_file):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            reclaimed += 1
            logger.info("reclaimed dead shm arena %s", arena)
    return reclaimed


class _ForkedProc:
    """subprocess.Popen-shaped handle over a zygote-forked worker.
    Liveness comes from the spawn connection the CHILD keeps open for its
    whole life (EOF ⇔ worker exited) — a bare pid probe would misread a
    recycled pid as a live worker after the zygote auto-reaps. Signals
    are only sent while the socket still shows the worker alive, which
    closes the signal-an-innocent-process window to the same EOF check."""

    def __init__(self, pid: int, liveness_sock):
        self.pid = pid
        self._sock = liveness_sock
        self._rc: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._rc is not None:
            return self._rc
        try:
            if self._sock.recv(1, socket_mod.MSG_PEEK) == b"":
                self._mark_dead()
        except (BlockingIOError, InterruptedError):
            return None  # no data, connection open: worker alive
        except OSError:
            self._mark_dead()
        return self._rc

    def _mark_dead(self) -> None:
        self._rc = -1
        try:
            self._sock.close()
        except OSError:
            pass

    def terminate(self) -> None:
        if self.poll() is None:
            try:
                os.kill(self.pid, signal.SIGTERM)
            except ProcessLookupError:
                self._mark_dead()

    def kill(self) -> None:
        if self.poll() is None:
            try:
                os.kill(self.pid, signal.SIGKILL)
            except ProcessLookupError:
                self._mark_dead()

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("forked-worker",
                                                timeout or 0)
            time.sleep(0.02)
        return self._rc or 0


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, proc: subprocess.Popen,
                 env_key: str):
        self.worker_id = worker_id
        self.proc = proc
        self.env_key = env_key
        self.address: Optional[Tuple[str, int]] = None
        self.ready = asyncio.Event()
        self.leased = False
        self.lifetime = "task"  # or "actor"
        self.resources: Optional[ResourceSet] = None
        self.pg_bundle: Optional[Tuple[bytes, int]] = None
        self.last_idle = time.monotonic()
        self.tpu_chips: List[int] = []


class Nodelet:
    def __init__(
        self,
        gcs_address: Tuple[str, int],
        session_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        node_name: str = "",
        labels: Optional[Dict[str, str]] = None,
    ):
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.server = RpcServer(host, port)
        self.node_name = node_name or self.node_id.hex()[:8]
        # Node labels (reference: the static node labels label_selector.h
        # matches against); node_name always present for affinity UX.
        self.labels = {**(labels or {}), "node_name": self.node_name}
        # Per-node worker-log namespace (session_dir may be shared across
        # nodes on one filesystem).
        self._worker_log_dir = os.path.join(
            self.session_dir, "logs", self.node_id.hex()[:8])
        # shape-key -> (resources, last_seen): lease shapes this node
        # couldn't satisfy (autoscaler demand signal via heartbeat).
        self._unmet_demand: Dict[str, Tuple[Dict[str, float], float]] = {}

        from ray_tpu._private.accelerators import detect_resources

        self.resources_total = dict(resources or detect_resources())
        self.resources_available = dict(self.resources_total)
        # TPU chip accounting for visibility enforcement (reference:
        # _private/accelerators/tpu.py:110 TPU_VISIBLE_CHIPS): whole-chip
        # leases get disjoint chip ids; fractional leases share chip 0.
        self._tpu_chips_free = list(range(int(
            self.resources_total.get("TPU", 0))))
        cfg = get_config()
        store_capacity = object_store_memory or cfg.object_store_memory
        os.makedirs(session_dir, exist_ok=True)
        self.store_path = os.path.join(
            "/dev/shm", f"ray_tpu_{os.path.basename(session_dir)}_{self.node_name}"
        )
        _sweep_dead_arenas()
        if os.path.exists(self.store_path):
            os.unlink(self.store_path)
        self.store = SharedMemoryStore(self.store_path, capacity=store_capacity,
                                       create=True)
        # Ownership marker: lets a later nodelet's sweep reclaim this arena if
        # this process dies without running stop() (SIGKILL'd driver etc.).
        try:
            with open(self.store_path + ".pid", "w") as f:
                f.write(str(os.getpid()))
        except OSError:
            pass
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self._gcs: Optional[RpcClient] = None
        self._background: List[asyncio.Task] = []
        # Spilled objects materialized for chunked transfer: id -> (obj, ts).
        self._transfer_cache: Dict[bytes, Tuple[Any, float]] = {}
        self._lease_waiters: List[asyncio.Event] = []
        # pg bundles: (pg_id, bundle_index) -> {"resources": .., "state": ..}
        self._bundles: Dict[Tuple[bytes, int], Dict[str, Any]] = {}
        self._shutting_down = False
        # Preforked worker template (started on first plain-CPU spawn).
        self._zygote_proc: Optional[subprocess.Popen] = None
        self._zygote_sock: str = ""
        # Lease RPCs run _spawn_worker via run_in_executor: without this
        # lock two concurrent leases could each see _zygote_proc is None
        # and Popen two zygotes on one socket path (the second unlinks and
        # rebinds the first's socket, leaking the first process).
        self._zygote_lock = threading.Lock()
        # (last observed log-lease value, local monotonic time first seen)
        self._log_lease_seen: Tuple[Optional[bytes], float] = (None, 0.0)
        # Kernel-level worker memory containment (reference:
        # common/cgroup/): applied at lease time for leases that carry a
        # "memory" resource; no-op where the hierarchy isn't writable.
        from ray_tpu._private.cgroups import CgroupManager

        self._cgroups = (CgroupManager(self.node_id.hex()[:8])
                         if get_config().enable_worker_cgroups else None)
        # Versioned resource view (ray_syncer analog): bumped on every
        # availability/demand change, pushed by _resource_sync_loop.
        # The Event exists from construction so bumps before the sync
        # loop's first iteration are not lost to the heartbeat fallback.
        self._resource_version = 0
        self._sync_event = asyncio.Event()

    # ------------------------------------------------------------------
    async def start(self) -> Tuple[str, int]:
        for name in dir(self):
            if name.startswith("rpc_"):
                self.server.register(name[4:], getattr(self, name))
        addr = await self.server.start()
        self._gcs = RpcClient(*self.gcs_address, name="gcs")
        await self._gcs.call_retrying(
            "register_node",
            node_id=self.node_id.binary(),
            address=addr,
            resources=self.resources_total,
            object_store_path=self.store_path,
            labels=self.labels,
        )
        self._background.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._background.append(
            asyncio.ensure_future(self._resource_sync_loop()))
        self._background.append(asyncio.ensure_future(self._reap_loop()))
        self._background.append(
            asyncio.ensure_future(self._memory_monitor_loop()))
        self._background.append(asyncio.ensure_future(self._log_monitor_loop()))
        # Metrics: this process has no Worker, so route registry flushes
        # through our own GCS client; the sampler loop feeds the per-node
        # gauges the Grafana cluster dashboard promises.
        loop = asyncio.get_running_loop()

        def _metrics_sink(key: str, payload: bytes) -> None:
            asyncio.run_coroutine_threadsafe(
                self._gcs.call("kv_put", key=key, value=payload), loop,
            ).result(timeout=10)

        um.set_flush_sink(_metrics_sink)
        self._background.append(asyncio.ensure_future(self._metrics_loop()))
        # Flight recorder: lag-sample this loop (worker loops attach in
        # EventLoopThread; the nodelet runs under asyncio.run).
        from ray_tpu._private import flight_recorder as _fr

        _fr.attach_loop(loop, "nodelet")
        logger.info("nodelet %s on %s:%d resources=%s", self.node_name, *addr,
                    self.resources_total)
        return addr

    async def stop(self) -> None:
        self._shutting_down = True
        for t in self._background:
            t.cancel()
        for w in list(self.workers.values()):
            if w.proc.poll() is None:
                w.proc.terminate()
        await asyncio.sleep(0)
        for w in list(self.workers.values()):
            try:
                w.proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                w.proc.kill()
        if self._zygote_proc is not None:
            try:
                self._zygote_proc.kill()
            except Exception:
                pass
            if self._zygote_sock and os.path.exists(self._zygote_sock):
                try:
                    os.unlink(self._zygote_sock)
                except OSError:
                    pass
        if self._gcs:
            await self._gcs.close()
        await self.server.stop()
        self.store.close()
        for p in (self.store_path, self.store_path + ".pid"):
            if os.path.exists(p):
                os.unlink(p)

    # ------------------------------------------------------------------
    # Log pipeline (reference: python/ray/_private/log_monitor.py — tail
    # worker log files → GCS pubsub → driver stdout)
    # ------------------------------------------------------------------
    async def _claim_component_log_lease(self, ttl: float
                                         ) -> Tuple[bool, bool]:
        """Refresh/claim the component-log-tailing lease. The value is
        (node_id, stamp) where the stamp exists only to make each refresh
        change the bytes: staleness is judged by observing the VALUE
        unchanged for ttl of LOCAL monotonic time, never by comparing a
        remote wall-clock stamp against ours (cross-node clock skew > ttl
        would otherwise create dueling leaders / premature takeover —
        ADVICE r4). kv_cas makes the takeover atomic under concurrent
        claimants. Returns (leader, took_over): took_over means the key
        previously named another node, so history already published by the
        old leader must not be re-shipped."""
        import pickle

        key = "logtail:component_leader"
        me = self.node_id.binary()
        cur = await self._gcs.call("kv_get", key=key)
        owner: Optional[bytes] = None
        if cur:
            try:
                owner, _ = pickle.loads(cur)
            except Exception:
                pass  # legacy/undecodable: stale once it stops changing
        now_m = time.monotonic()
        if cur is not None and owner != me:
            seen_val, seen_at = self._log_lease_seen
            if seen_val != cur:
                # value moved since our last probe: holder is alive
                self._log_lease_seen = (cur, now_m)
                return False, False
            if now_m - seen_at <= ttl:
                return False, False
        new = pickle.dumps((me, time.time()))
        won = bool(await self._gcs.call("kv_cas", key=key,
                                        expect=cur, value=new))
        if won:
            self._log_lease_seen = (new, now_m)
        return won, won and cur is not None and owner != me

    async def _log_monitor_loop(self) -> None:
        # Tail only THIS node's worker logs. Multi-node clusters sharing one
        # filesystem (cluster_utils, fake TPU-pod transport) would otherwise
        # have N nodelets each republishing every worker's output with the
        # wrong node label. Component logs (gcs.log, nodelet-*.log) live at
        # the top level of the shared logs dir; exactly one nodelet holds a
        # LEASED kv key for them (timestamp refreshed while alive) so that a
        # dead leader — or stale node ids left in a persistent sqlite-backed
        # store across cluster restarts — is replaced instead of orphaning
        # component-log tailing forever.
        log_dir = self._worker_log_dir
        component_dir = ""
        lease_ttl = 10.0
        next_lease_at = 0.0
        offsets: Dict[str, int] = {}
        partial: Dict[str, bytes] = {}
        while not self._shutting_down:
            await asyncio.sleep(0.5)
            try:
                now = time.time()
                if self._gcs is not None and now >= next_lease_at:
                    leader, took_over = (
                        await self._claim_component_log_lease(lease_ttl))
                    component_dir = (os.path.join(self.session_dir, "logs")
                                     if leader else "")
                    if took_over and component_dir:
                        # Start tailing at the CURRENT end of each component
                        # file: the dead leader already published history,
                        # and re-shipping it would duplicate driver output.
                        for n in sorted(os.listdir(component_dir)):
                            p = os.path.join(component_dir, n)
                            if os.path.isfile(p) and p not in offsets:
                                try:
                                    offsets[p] = os.path.getsize(p)
                                except OSError:
                                    pass
                    # Holders refresh well inside the ttl; others probe at
                    # ttl pace so takeover happens within ~2 ttl.
                    next_lease_at = now + (lease_ttl / 3 if leader
                                           else lease_ttl)
                names = [
                    (log_dir, n)
                    for n in (sorted(os.listdir(log_dir))
                              if os.path.isdir(log_dir) else [])]
                if component_dir:
                    names += [
                        (component_dir, n)
                        for n in sorted(os.listdir(component_dir))
                        if os.path.isfile(os.path.join(component_dir, n))]
                batches = []
                for dirpath, name in names:
                    if not name.endswith(".log"):
                        continue
                    path = os.path.join(dirpath, name)
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        continue
                    pos = offsets.get(path, 0)
                    if size <= pos:
                        continue
                    with open(path, "rb") as f:
                        f.seek(pos)
                        chunk = partial.pop(path, b"") + f.read(
                            min(size - pos, 512 * 1024))
                        offsets[path] = f.tell()
                    *lines, rest = chunk.split(b"\n")
                    if rest:
                        partial[path] = rest
                    lines = [ln.decode("utf-8", "replace") for ln in lines
                             if ln.strip()]
                    # Ship everything read (offsets already advanced past
                    # it) — in capped batches, never by dropping.
                    for j in range(0, len(lines), 200):
                        batches.append({
                            "source": name[:-len(".log")],
                            "node": self.node_name,
                            "lines": lines[j:j + 200],
                        })
                if batches and self._gcs is not None:
                    await self._gcs.notify(
                        "publish", channel="logs", message=batches)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # log shipping must never hurt the node

    # ------------------------------------------------------------------
    # Worker pool (reference: worker_pool.h:283)
    # ------------------------------------------------------------------
    def _spawn_worker(self, env_key: str,
                      runtime_env: Optional[Dict[str, Any]],
                      needs_tpu: bool = False,
                      tpu_chips: Optional[List[int]] = None,
                      env_updates: Optional[Dict[str, str]] = None
                      ) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        env.update(env_updates or {})
        if needs_tpu and tpu_chips:
            env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, tpu_chips))
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,1,{len(tpu_chips)}"
        if not needs_tpu:
            # Workers without a TPU lease start WITHOUT the TPU plumbing:
            # the site hook imports jax at interpreter start (~2s of the
            # ~2.3s worker spawn) and would contend for the chip. TPU
            # leases (num_tpus>0) get the full environment — this is the
            # visibility-enforcement hook (reference: TPU_VISIBLE_CHIPS in
            # accelerators/tpu.py:110).
            env.pop("PALLAS_AXON_POOL_IPS", None)
            if env.get("JAX_PLATFORMS") == "axon":
                env["JAX_PLATFORMS"] = "cpu"
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODELET_ADDR"] = f"{self.server.host}:{self.server.port}"
        env["RAY_TPU_GCS_ADDR"] = f"{self.gcs_address[0]}:{self.gcs_address[1]}"
        env["RAY_TPU_STORE_PATH"] = self.store_path
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_NODE_NAME"] = self.node_name
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        prepend = env.pop("RAY_TPU_PYTHONPATH_PREPEND", "")
        if prepend:
            env["PYTHONPATH"] = prepend + os.pathsep + env["PYTHONPATH"]
        if runtime_env:
            for k, v in (runtime_env.get("env_vars") or {}).items():
                env[k] = v
        log_dir = self._worker_log_dir
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:8]}.log")
        # pip/uv runtime envs run the worker under their venv's interpreter
        # (reference: runtime_env/pip.py py_executable override).
        python = env.pop("RAY_TPU_PYTHON_EXECUTABLE", sys.executable)
        # Fast path: plain CPU workers fork from the preforked zygote
        # (~ms instead of ~0.6s interpreter+import start). TPU workers
        # need a fresh interpreter (per-process PJRT registration), and
        # custom interpreters / runtime envs take the classic spawn.
        proc: Any = None
        if (not needs_tpu and python == sys.executable
                and not runtime_env):
            forked = self._spawn_from_zygote(env, log_path)
            if forked is not None:
                proc = _ForkedProc(*forked)
        if proc is None:
            out = open(log_path, "wb")
            proc = subprocess.Popen(
                [python, "-m", "ray_tpu._private.worker_main"],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        handle = WorkerHandle(worker_id, proc, env_key)
        self.workers[worker_id] = handle
        return handle

    def _spawn_from_zygote(self, env: Dict[str, str], log_path: str
                           ) -> Optional[Tuple[int, Any]]:
        """Fork a worker from the zygote, starting it on first use.
        Returns None (→ classic spawn) when the zygote is unavailable."""
        from ray_tpu._private.zygote import spawn_via_zygote

        with self._zygote_lock:
            if (self._zygote_proc is not None
                    and self._zygote_proc.poll() is not None):
                self._zygote_proc = None  # died: restart on next spawn
            if self._zygote_proc is None:
                sock = os.path.join(self.session_dir,
                                    f"zygote-{self.node_id.hex()[:8]}.sock")
                zenv = dict(os.environ)
                zenv.pop("PALLAS_AXON_POOL_IPS", None)
                if zenv.get("JAX_PLATFORMS") == "axon":
                    zenv["JAX_PLATFORMS"] = "cpu"
                zenv["RAY_TPU_ZYGOTE_SOCKET"] = sock
                repo_root = os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                zenv["PYTHONPATH"] = (repo_root + os.pathsep
                                      + zenv.get("PYTHONPATH", ""))
                self._zygote_sock = sock
                self._zygote_proc = subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu._private.zygote"],
                    env=zenv, start_new_session=True)
                deadline = time.monotonic() + 20.0
                while (not os.path.exists(sock)
                       and time.monotonic() < deadline
                       and self._zygote_proc.poll() is None):
                    time.sleep(0.01)
        try:
            get_chaos().failpoint("nodelet.zygote_fork")
            return spawn_via_zygote(self._zygote_sock, env, log_path)
        except Exception:
            logger.warning("zygote spawn failed; falling back to exec",
                           exc_info=True)
            return None

    async def rpc_register_worker(
        self, worker_id: bytes, address: Tuple[str, int]
    ) -> Dict[str, Any]:
        """Called by a freshly-started worker process."""
        wid = WorkerID(worker_id)
        handle = self.workers.get(wid)
        if handle is None:
            return {"ok": False}
        handle.address = tuple(address)
        handle.ready.set()
        return {"ok": True}

    async def _get_idle_worker(
        self, env_key: str, runtime_env: Optional[Dict[str, Any]],
        needs_tpu: bool = False, tpu_chips: Optional[List[int]] = None,
    ) -> WorkerHandle:
        """Returns a worker already marked leased — reserving at selection
        time closes the race where two lease requests pick the same worker
        (one scanning the pool while the other awaits its spawned worker's
        ready event)."""
        for w in self.workers.values():
            if (not w.leased and w.env_key == env_key and w.ready.is_set()
                    and w.proc.poll() is None):
                w.leased = True
                self._maybe_prewarm(env_key)
                return w
        env_updates: Dict[str, str] = {}
        if runtime_env and (runtime_env.get("working_dir")
                            or runtime_env.get("py_modules")
                            or runtime_env.get("pip")
                            or runtime_env.get("uv")):
            from ray_tpu._private.runtime_env import materialize

            env_updates = await materialize(
                runtime_env, self._gcs,
                os.path.join(self.session_dir, "runtime_envs"))
        # Off-loop: the zygote round trip (and its one-time ~0.6s startup)
        # and Popen() must not stall RPC/heartbeat handling.
        handle = await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._spawn_worker(
                env_key, runtime_env, needs_tpu, tpu_chips, env_updates))
        handle.leased = True
        self._maybe_prewarm(env_key)
        try:
            await asyncio.wait_for(handle.ready.wait(),
                                   get_config().worker_start_timeout_s)
        except BaseException:
            handle.leased = False
            if handle.proc.poll() is None:
                handle.proc.terminate()
            self.workers.pop(handle.worker_id, None)
            raise
        return handle

    def _maybe_prewarm(self, env_key: str) -> None:
        """Keep a small reserve of BOOTED plain-CPU workers ahead of
        demand (reference: the WorkerPool's prestarted python workers).
        Forking + boot (~10-20 ms each) then happens in the background
        between lease waves instead of on the bring-up critical path —
        actor/worker churn overlaps its spawn cost with driver-side work."""
        cfg = get_config()
        if env_key != "" or cfg.worker_prewarm <= 0:
            return  # only the vanilla pool is predictably reusable
        if self.__dict__.get("_prewarming"):
            return
        idle = sum(1 for w in self.workers.values()
                   if not w.leased and w.env_key == ""
                   and w.proc.poll() is None)
        want = min(cfg.worker_prewarm - idle,
                   max(0, cfg.worker_pool_max - len(self.workers)))
        if want <= 0:
            return
        self.__dict__["_prewarming"] = True

        async def _replenish(n: int) -> None:
            loop = asyncio.get_running_loop()
            try:
                for _ in range(n):
                    try:
                        await loop.run_in_executor(
                            None, lambda: self._spawn_worker(
                                "", None, False, None, {}))
                    except Exception:
                        return  # zygote down / spawn failing: stop quietly
            finally:
                self.__dict__["_prewarming"] = False

        asyncio.ensure_future(_replenish(want))

    # ------------------------------------------------------------------
    # Leases (reference: RequestWorkerLease node_manager.proto:394 +
    # LocalTaskManager dispatch)
    # ------------------------------------------------------------------
    async def rpc_lease_worker(
        self,
        resources: Dict[str, float],
        runtime_env: Optional[Dict[str, Any]] = None,
        lifetime: str = "task",
        pg_bundle: Optional[Tuple[bytes, int]] = None,
        block: bool = True,
        owner: Optional[List[Any]] = None,
    ) -> Dict[str, Any]:
        req = ResourceSet(resources)
        num_tpus = float(resources.get("TPU", 0) or 0)
        needs_tpu = num_tpus > 0
        env_key = repr(sorted((runtime_env or {}).items())) + (
            "|tpu" if needs_tpu else "")
        cfg = get_config()
        t_req = time.monotonic()
        queued_counted = False
        deadline = time.monotonic() + cfg.worker_start_timeout_s
        while True:
            pool = self._bundle_pool(pg_bundle)
            if pool is None:
                return {"ok": False, "error": "unknown placement bundle"}
            if req.fits_in(pool):
                # Failpoint BEFORE any accounting mutates: an injected
                # grant failure/delay must never leak reserved resources.
                # The await yields the loop, so re-check fitness after —
                # a concurrent grant may have taken the resources.
                chaos = get_chaos()
                if chaos.enabled:
                    await chaos.failpoint_async("nodelet.lease_grant")
                    if not req.fits_in(pool):
                        continue
                req.subtract_from(pool)
                self._bump_resources()
                # Disjoint chip assignment per whole-chip lease; fractional
                # leases share chip 0 (reference: tpu.py visibility).
                chips: List[int] = []
                if needs_tpu:
                    if num_tpus >= 1 and self._tpu_chips_free:
                        chips = sorted(self._tpu_chips_free[-int(num_tpus):])
                        del self._tpu_chips_free[-int(num_tpus):]
                    else:
                        chips = [0]
                    env_key += f"|chips:{','.join(map(str, chips))}"
                try:
                    worker = await self._get_idle_worker(env_key, runtime_env,
                                                         needs_tpu, chips)
                except Exception as e:
                    req.add_to(pool)
                    self._bump_resources()  # rollback must sync too, or
                    # the GCS under-schedules this node for a heartbeat
                    if num_tpus >= 1:
                        self._tpu_chips_free.extend(chips)
                    return {"ok": False, "error": f"worker start failed: {e!r}"}
                worker.leased = True
                worker.lifetime = lifetime
                worker.lease_owner = tuple(owner) if owner else None
                worker.resources = req
                mem = float(resources.get("memory", 0) or 0)
                if mem > 0 and self._cgroups is not None                         and self._cgroups.available:
                    worker.cgroup_limited = self._cgroups.limit_worker(
                        worker.worker_id.hex()[:12], worker.proc.pid,
                        int(mem))
                worker.pg_bundle = pg_bundle
                worker.tpu_chips = chips if num_tpus >= 1 else []
                _m_leases_granted().inc(tags={"node": self.node_name})
                _m_sched_latency().observe(time.monotonic() - t_req,
                                           tags={"node": self.node_name})
                return {
                    "ok": True,
                    "worker_id": worker.worker_id.binary(),
                    "worker_address": worker.address,
                    "node_id": self.node_id.binary(),
                    # Other lease requests are parked on this node RIGHT
                    # NOW: the grantee's pump must not linger-hold the
                    # worker when its queue idles (a 0.2 s idle hold per
                    # rotation starves contending submitters ~5x on a
                    # worker-starved node).
                    "contended": bool(self._lease_waiters),
                }
            if not queued_counted:
                queued_counted = True
                _m_leases_queued().inc(tags={"node": self.node_name})
            if not block:
                if pg_bundle is None:
                    # PG-bundle leases are pinned to this node; a new node
                    # could never satisfy them (pending-PG demand is
                    # counted separately by the autoscaler).
                    self._record_unmet_demand(resources)
                return {"ok": False, "error": "resources unavailable",
                        "retry": True}
            if time.monotonic() > deadline:
                if pg_bundle is None:
                    self._record_unmet_demand(resources)
                return {"ok": False, "error": "lease timeout", "retry": True}
            event = asyncio.Event()
            self._lease_waiters.append(event)
            try:
                await asyncio.wait_for(event.wait(), 1.0)
            except asyncio.TimeoutError:
                pass
            finally:
                if event in self._lease_waiters:
                    self._lease_waiters.remove(event)

    def _bundle_pool(self, pg_bundle) -> Optional[Dict[str, float]]:
        if pg_bundle is None:
            return self.resources_available
        entry = self._bundles.get((bytes(pg_bundle[0]), int(pg_bundle[1])))
        if entry is None or entry["state"] != "committed":
            return None
        return entry["available"]

    async def rpc_return_worker(
        self, worker_id: bytes, kill: bool = False
    ) -> Dict[str, Any]:
        wid = WorkerID(worker_id)
        worker = self.workers.get(wid)
        if worker is None:
            return {"ok": False}
        if worker.resources is not None:
            pool = self._bundle_pool(getattr(worker, "pg_bundle", None))
            if pool is not None:
                worker.resources.add_to(pool)
            worker.resources = None
        if worker.tpu_chips:
            self._tpu_chips_free.extend(worker.tpu_chips)
            worker.tpu_chips = []
        worker.leased = False
        worker.last_idle = time.monotonic()
        if getattr(worker, "cgroup_limited", False)                 and self._cgroups is not None:
            self._cgroups.relax_worker(worker.worker_id.hex()[:12])
            worker.cgroup_limited = False
        self._wake_lease_waiters()
        if kill and worker.proc.poll() is None:
            worker.proc.terminate()
        return {"ok": True}

    def _wake_lease_waiters(self) -> None:
        for event in self._lease_waiters:
            event.set()
        self._bump_resources()

    # ------------------------------------------------------------------
    # Resource syncer (reference: common/ray_syncer — versioned resource
    # views pushed on CHANGE over a bidi stream, not polled; here a
    # debounced push RPC with a monotonic version, with the heartbeat as
    # the liveness carrier and periodic full-snapshot fallback)
    # ------------------------------------------------------------------
    def _bump_resources(self) -> None:
        """Mark the resource view dirty: bumps the version and kicks the
        sync loop so the GCS sees the change within the debounce window
        (~50 ms), not a heartbeat period later."""
        self._resource_version += 1
        self._sync_event.set()

    async def _resource_sync_loop(self) -> None:
        while not self._shutting_down:
            try:
                await self._sync_event.wait()
                await asyncio.sleep(0.05)  # debounce bursts of changes
                self._sync_event.clear()
                version = self._resource_version
                await self._gcs.call(
                    "sync_resources",
                    node_id=self.node_id.binary(),
                    version=version,
                    resources_available=dict(self.resources_available),
                    demand=self._demand_snapshot(),
                )
            except asyncio.CancelledError:
                return
            except Exception:
                # Dropped sync: the next change or heartbeat (which also
                # carries the version) re-converges the view.
                await asyncio.sleep(0.5)

    # ------------------------------------------------------------------
    # Placement group bundles: 2-phase prepare/commit (reference:
    # placement_group_resource_manager.h:50,90)
    # ------------------------------------------------------------------
    async def rpc_prepare_bundle(
        self, pg_id: bytes, bundle_index: int, resources: Dict[str, float]
    ) -> Dict[str, Any]:
        req = ResourceSet(resources)
        if not req.fits_in(self.resources_available):
            return {"ok": False, "error": "insufficient resources"}
        req.subtract_from(self.resources_available)
        self._bump_resources()
        self._bundles[(pg_id, bundle_index)] = {
            "resources": dict(req), "available": dict(req), "state": "prepared",
        }
        return {"ok": True}

    async def rpc_commit_bundle(self, pg_id: bytes,
                                bundle_index: int) -> Dict[str, Any]:
        entry = self._bundles.get((pg_id, bundle_index))
        if entry is None:
            return {"ok": False}
        entry["state"] = "committed"
        self._wake_lease_waiters()
        return {"ok": True}

    async def rpc_return_bundle(self, pg_id: bytes,
                                bundle_index: int) -> Dict[str, Any]:
        entry = self._bundles.pop((pg_id, bundle_index), None)
        if entry is not None:
            ResourceSet(entry["resources"]).add_to(self.resources_available)
            self._wake_lease_waiters()
        return {"ok": True}

    # ------------------------------------------------------------------
    # Introspection / state API support
    # ------------------------------------------------------------------
    async def rpc_node_stats(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id.binary(),
            "node_name": self.node_name,
            "resources_total": self.resources_total,
            "resources_available": dict(self.resources_available),
            "num_workers": len(self.workers),
            "num_leased": sum(1 for w in self.workers.values() if w.leased),
            "workers": [
                {
                    "worker_id": w.worker_id.hex(),
                    "pid": w.proc.pid,
                    "leased": w.leased,
                    "lifetime": w.lifetime,
                    "address": w.address,
                    "tpu_chips": list(w.tpu_chips),
                }
                for w in self.workers.values()
            ],
            "store": self.store.stats(),
            "store_path": self.store_path,
            "bundles": {
                f"{k[0].hex()[:8]}:{k[1]}": v["state"]
                for k, v in self._bundles.items()
            },
        }

    def _read_object_for_transfer(self, object_id: bytes):
        """Sealed object lookup (shm, then spill) shared by the whole-object
        and chunked fetch paths. Shm reads are cheap memoryviews; a SPILLED
        object materializes from disk, so a chunked pull must not re-read
        the whole file per chunk — recently-materialized spilled objects are
        held in a tiny TTL cache for the duration of the transfer."""
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID(object_id)
        obj = self.store.get_serialized(oid)
        if obj is not None:
            return obj
        now = time.monotonic()
        cached = self._transfer_cache.get(object_id)
        if cached is not None and now - cached[1] < 30.0:
            self._transfer_cache[object_id] = (cached[0], now)
            return cached[0]
        from ray_tpu.core.object_store import spill_read

        obj = spill_read(os.path.join(
            self.session_dir, "spill", self.node_id.hex()), oid)
        if obj is not None:
            self._transfer_cache[object_id] = (obj, now)
            # Evict stale entries so the cache never outgrows one or two
            # in-flight transfers.
            for k in [k for k, (_, ts) in self._transfer_cache.items()
                      if now - ts > 30.0]:
                self._transfer_cache.pop(k, None)
        return obj

    async def rpc_fetch_object_info(
            self, object_id: bytes,
            inline_below: int = 0) -> Optional[Dict[str, Any]]:
        """Chunked-pull step 1: sizes, so the puller can plan chunk ranges
        and apply admission control (reference: PullManager learns object
        sizes before activating pulls, pull_manager.h:49). Objects at or
        under `inline_below` come back whole in this same reply — the
        common small-object fetch stays one RPC."""
        obj = self._read_object_for_transfer(object_id)
        if obj is None:
            return None
        sizes = [len(b) for b in obj.buffers]
        if inline_below and sum(sizes) <= inline_below:
            return {
                "metadata": bytes(obj.metadata),
                "sizes": sizes,
                "buffers": [bytes(b) for b in obj.buffers],
            }
        return {"metadata": bytes(obj.metadata), "sizes": sizes}

    # Peer-serving directory: object id -> chunk offset -> puller worker
    # addresses known (from pull acks) to hold that chunk. Bounded; a
    # stale entry just costs the redirected puller one fallback RPC.
    _CHUNK_DIR_MAX_OBJECTS = 16

    def _learn_chunk_locations(self, object_id: bytes, puller, have) -> None:
        if not puller or not have:
            return
        directory = self.__dict__.setdefault("_chunk_dir", {})
        if object_id not in directory \
                and len(directory) >= self._CHUNK_DIR_MAX_OBJECTS:
            directory.pop(next(iter(directory)))
        entry = directory.setdefault(object_id, {})
        addr = tuple(puller)
        for off in have:
            holders = entry.setdefault(int(off), [])
            if addr not in holders:
                holders.append(addr)

    def _chunk_redirect(self, object_id: bytes, offset: int,
                        puller) -> Optional[List[Any]]:
        """When another puller already holds this chunk, alternate between
        serving bytes and handing out the peer's address — the owner
        becomes a distribution-tree ROOT serving ~half the load while
        peers fan out the rest (reference: push_manager.h:27 /
        pull_manager.h:49). The 50/50 split self-balances on a node that
        is the sole source: redirecting everything would idle the owner's
        own bandwidth."""
        if not puller:
            return None
        entry = self.__dict__.get("_chunk_dir", {}).get(object_id)
        if not entry:
            return None
        holders = [a for a in entry.get(int(offset), ())
                   if a != tuple(puller)]
        if not holders:
            return None
        rr = self.__dict__.get("_redir_rr", 0) + 1
        self.__dict__["_redir_rr"] = rr
        if rr % 2 == 0:
            return None  # owner serves this one directly
        return list(holders[rr % len(holders)])

    async def rpc_fetch_object_chunk(
            self, object_id: bytes, offset: int, length: int,
            puller: Optional[List[Any]] = None,
            have: Optional[List[int]] = None,
            no_redirect: bool = False) -> Optional[Dict[str, Any]]:
        """Chunked-pull step 2: one slice of the logical concatenation of
        the object's buffers (reference: ObjectManager chunked Push/Pull,
        object_buffer_pool.h). The slice ships as a pickle-5 out-of-band
        buffer: when it falls inside one source buffer (the common case —
        one numpy payload) it is a zero-copy view of the shm arena all the
        way to the socket (the view holds the arena read pin); spans are
        assembled once into a bytearray, still oob on the wire.

        `puller`+`have` piggyback the caller's landed chunks (pull acks);
        under concurrent pressure the reply may be {"redirect": addr}
        pointing at a peer that holds the chunk (no_redirect forces
        bytes — the fallback after a failed peer fetch)."""
        self._learn_chunk_locations(object_id, puller, have)
        if not no_redirect:
            redirect = self._chunk_redirect(object_id, offset, puller)
            if redirect is not None:
                return {"redirect": redirect}
        return await self._serve_chunk(object_id, offset, length)

    async def _serve_chunk(self, object_id: bytes, offset: int,
                           length: int) -> Optional[Dict[str, Any]]:
        import pickle

        obj = self._read_object_for_transfer(object_id)
        if obj is None:
            return None
        spans = []
        pos = 0
        for buf in obj.buffers:
            n = len(buf)
            if pos + n <= offset:
                pos += n
                continue
            start = max(0, offset - pos)
            take = min(n - start, offset + length - (pos + start))
            if take > 0:
                spans.append(memoryview(buf)[start:start + take])
            pos += n
            if sum(len(s) for s in spans) >= length:
                break
        if len(spans) == 1:
            return {"data": pickle.PickleBuffer(spans[0])}
        out = bytearray()
        for s in spans:
            out += s
        return {"data": pickle.PickleBuffer(out)}

    async def rpc_ping(self) -> str:
        return "pong"

    # ------------------------------------------------------------------
    # Profiling / debugging endpoints (reference: the per-node dashboard
    # agent's reporter module — py-spy stack dumps and psutil process
    # stats, dashboard/modules/reporter/; here native: sys._current_frames
    # in-worker and /proc sampling here)
    # ------------------------------------------------------------------
    async def _fanout_workers(self, method: str, *, timeout: float = 10.0,
                              worker_id_prefix: str = "",
                              **kwargs) -> Dict[str, Any]:
        """Call one RPC on every live worker concurrently, error-wrapped
        per worker (shared scaffolding for the reporter endpoints)."""

        async def _one(wid, w):
            client = None
            try:
                client = RpcClient(*w.address, name=method)
                return wid.hex()[:12], await client.call(
                    method, timeout=timeout, **kwargs)
            except Exception as e:  # noqa: BLE001
                return wid.hex()[:12], {"error": repr(e)}
            finally:
                if client is not None:
                    try:
                        await client.close()
                    except Exception:
                        pass

        targets = [(wid, w) for wid, w in list(self.workers.items())
                   if w.proc.poll() is None and w.address is not None
                   and wid.hex().startswith(worker_id_prefix)]
        pairs = await asyncio.gather(*[_one(wid, w) for wid, w in targets])
        return {"node": self.node_name, "workers": dict(pairs)}

    async def rpc_node_stacks(self) -> Dict[str, Any]:
        """All-thread python stacks for every live worker on this node,
        gathered concurrently (the `ray stack` surface)."""
        return await self._fanout_workers("dump_stacks")

    async def rpc_node_overhead(self) -> Dict[str, Any]:
        """Sampled per-call overhead decomposition from every live worker
        on this node (flight recorder; `ray_tpu profile --overhead`)."""
        return await self._fanout_workers("overhead_breakdown")

    async def rpc_node_flight_record(self) -> Dict[str, Any]:
        """Flight-recorder ring dumps: every live worker's, plus this
        nodelet's own (`ray_tpu debug flight-record`)."""
        from ray_tpu._private import flight_recorder as _fr

        out = await self._fanout_workers("flight_record")
        out["nodelet"] = _fr.flight_snapshot()
        return out

    async def rpc_profile_workers(self, kind: str = "cpu",
                                  duration: float = 5.0,
                                  hz: float = 99.0,
                                  worker_id_prefix: str = "",
                                  top: int = 50) -> Dict[str, Any]:
        """Run the sampling CPU profiler (kind="cpu" → folded stacks) or
        the tracemalloc heap profiler (kind="heap") inside this node's
        workers, concurrently (reference: reporter agent py-spy/memray
        endpoints, dashboard/modules/reporter/). worker_id_prefix narrows
        to one worker; default profiles every live worker on the node."""
        method = "cpu_profile" if kind == "cpu" else "heap_profile"
        kwargs = ({"duration": duration, "hz": hz} if kind == "cpu"
                  else {"duration": duration, "top": top})
        return await self._fanout_workers(
            method, timeout=duration + 30,
            worker_id_prefix=worker_id_prefix, **kwargs)

    async def rpc_node_proc_stats(self) -> Dict[str, Any]:
        """Per-worker process stats from /proc (cpu seconds, rss, threads)
        plus the nodelet's own — the reporter-agent metrics floor."""
        out: Dict[str, Any] = {"node": self.node_name, "procs": {}}
        pids = {"nodelet": os.getpid()}
        for wid, w in list(self.workers.items()):
            if w.proc.poll() is None:
                pids[wid.hex()[:12]] = w.proc.pid
        page = os.sysconf("SC_PAGE_SIZE")
        tick = os.sysconf("SC_CLK_TCK")
        for label, pid in pids.items():
            try:
                with open(f"/proc/{pid}/stat") as f:
                    parts = f.read().rsplit(")", 1)[1].split()
                utime, stime = int(parts[11]), int(parts[12])
                threads = int(parts[17])
                with open(f"/proc/{pid}/statm") as f:
                    rss_pages = int(f.read().split()[1])
                out["procs"][label] = {
                    "pid": pid,
                    "cpu_seconds": round((utime + stime) / tick, 2),
                    "rss_mb": round(rss_pages * page / 2**20, 1),
                    "num_threads": threads,
                }
            except OSError:
                pass
        return out

    # ------------------------------------------------------------------
    # Background loops
    # ------------------------------------------------------------------
    def _record_unmet_demand(self, resources: Dict[str, float]) -> None:
        """Resource shapes this node could not lease — carried on the next
        heartbeat so the autoscaler sees TASK demand, not just pending
        actors/PGs (reference: resource_demand in the load report,
        raylet's ResourceLoad)."""
        key = repr(sorted(resources.items()))
        self._unmet_demand[key] = (dict(resources), time.monotonic())
        self._bump_resources()

    def _demand_snapshot(self) -> List[Dict[str, float]]:
        cutoff = time.monotonic() - 30.0
        for key, (_, ts) in list(self._unmet_demand.items()):
            if ts < cutoff:
                del self._unmet_demand[key]
        return [shape for shape, _ in self._unmet_demand.values()]

    async def _metrics_loop(self) -> None:
        """Per-node runtime gauges (reference: the reporter agent's psutil
        sampling -> OpenCensus gauges): resource availability, leased
        workers, object-store usage, and per-worker RSS. Labelled gauges
        are cleared each round so series for dead workers don't linger."""
        node = self.node_name
        g_avail = um.get_gauge(
            "ray_tpu_resource_available",
            "Schedulable capacity currently available on the node",
            tag_keys=("node", "resource"))
        g_leased = um.get_gauge(
            "ray_tpu_workers_leased",
            "Worker processes currently leased out on the node",
            tag_keys=("node",))
        g_workers = um.get_gauge(
            "ray_tpu_workers_alive",
            "Worker processes alive in the node's pool",
            tag_keys=("node",))
        g_store = um.get_gauge(
            "ray_tpu_object_store_bytes_in_use",
            "Bytes resident in the node's shared-memory object store",
            tag_keys=("node",))
        g_rss = um.get_gauge(
            "ray_tpu_worker_rss_mb",
            "Resident set size of each live worker process (MiB)",
            tag_keys=("node", "worker"))
        # Pre-register the node's counters/histograms at zero so every
        # dashboard-promised series exists from node start, not from the
        # first lease / first spill.
        from ray_tpu.core.object_store import (
            _arena_puts_counter,
            _spilled_bytes_counter,
            _spilled_objects_counter,
        )

        _m_leases_granted().inc(0, tags={"node": node})
        _m_leases_queued().inc(0, tags={"node": node})
        _m_sched_latency()
        _spilled_objects_counter().inc(0)
        _spilled_bytes_counter().inc(0)
        _arena_puts_counter()
        page = os.sysconf("SC_PAGE_SIZE")
        while not self._shutting_down:
            await asyncio.sleep(2.0)
            try:
                g_avail.set_many(
                    [({"node": node, "resource": res}, v)
                     for res, v in dict(self.resources_available).items()])
                live = [(wid, w) for wid, w in list(self.workers.items())
                        if w.proc.poll() is None]
                g_leased.set(sum(1 for _, w in live if w.leased),
                             tags={"node": node})
                g_workers.set(len(live), tags={"node": node})
                try:
                    g_store.set(
                        float(self.store.stats().get("bytes_in_use", 0)),
                        tags={"node": node})
                except Exception:
                    pass
                rss_items = []
                for wid, w in live:
                    try:
                        with open(f"/proc/{w.proc.pid}/statm") as f:
                            rss_pages = int(f.read().split()[1])
                    except (OSError, ValueError, IndexError):
                        continue
                    rss_items.append((
                        {"node": node, "worker": wid.hex()[:12]},
                        round(rss_pages * page / 2**20, 1)))
                # Atomic replace: dead workers' series drop without a
                # clear-then-set window a concurrent flush could snapshot.
                g_rss.set_many(rss_items)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # sampling must never hurt the node

    async def _heartbeat_loop(self) -> None:
        cfg = get_config()
        while not self._shutting_down:
            try:
                # Timeout near the beat period, not gcs_rpc_timeout_s: if
                # the GCS received the beat but the ack is lost (one-way
                # partition), a 30s stall here would miss enough beats to
                # get this node declared dead even though its beats arrive.
                reply = await self._gcs.call(
                    "heartbeat",
                    node_id=self.node_id.binary(),
                    resources_available=dict(self.resources_available),
                    demand=self._demand_snapshot(),
                    version=self._resource_version,
                    timeout=max(2 * cfg.heartbeat_interval_s, 2.0),
                )
                if not reply.get("ok") and reply.get("reregister"):
                    # GCS declared us dead (transient stall past the failure
                    # threshold) or restarted without our record: rejoin.
                    logger.warning("GCS lost this node; re-registering")
                    await self._gcs.call(
                        "register_node",
                        node_id=self.node_id.binary(),
                        address=(self.server.host, self.server.port),
                        resources=self.resources_total,
                        object_store_path=self.store_path,
                        labels=self.labels,
                    )
            except Exception as e:
                logger.warning("heartbeat failed: %r", e)
            await asyncio.sleep(cfg.heartbeat_interval_s)

    def _memory_usage(self) -> float:
        cfg = get_config()
        if cfg.testing_memory_usage >= 0:
            return cfg.testing_memory_usage
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    info[k] = int(v.split()[0])
            return 1.0 - info["MemAvailable"] / info["MemTotal"]
        except Exception:
            return 0.0

    async def _memory_monitor_loop(self) -> None:
        """OOM protection (reference: memory_monitor.h polling + the
        retriable-LIFO worker killing policy, worker_killing_policy.h:69):
        above the usage threshold, kill the most recently leased task
        worker — its task retries elsewhere/later; actors are spared first
        (their state is harder to recover)."""
        from ray_tpu.core.oom_policies import get_policy

        cfg = get_config()
        if cfg.memory_usage_threshold <= 0:
            return
        policy = get_policy(cfg.oom_killer_policy)
        while not self._shutting_down:
            await asyncio.sleep(cfg.memory_monitor_interval_s)
            usage = self._memory_usage()
            if usage < cfg.memory_usage_threshold:
                continue
            leased = [w for w in self.workers.values()
                      if w.leased and w.proc.poll() is None]
            if not leased:
                continue
            victim = policy.select(leased)
            if victim is None:
                continue
            logger.warning(
                "memory pressure %.0f%% >= %.0f%%: killing worker %s "
                "(%s policy)", usage * 100,
                cfg.memory_usage_threshold * 100,
                victim.worker_id.hex()[:8], policy.name)
            try:
                victim.proc.kill()
            except Exception:
                pass
            # Let the reap loop handle resource return + death report.
            await asyncio.sleep(1.0)

    async def _reap_loop(self) -> None:
        """Detect dead workers; release their resources; tell GCS (reference:
        NodeManager worker-failure handling + plasma client disconnect)."""
        cfg = get_config()
        idle_ttl = 60.0
        while not self._shutting_down:
            await asyncio.sleep(0.2)
            # Expire transfer-cache entries even when no further fetch ever
            # arrives — a finished chunked pull must not pin a materialized
            # multi-GB spilled object for the nodelet's lifetime.
            if self._transfer_cache:
                now = time.monotonic()
                for k in [k for k, (_, ts) in self._transfer_cache.items()
                          if now - ts > 30.0]:
                    self._transfer_cache.pop(k, None)
            for wid, w in list(self.workers.items()):
                code = w.proc.poll()
                if code is not None:
                    del self.workers[wid]
                    if w.resources is not None:
                        pool = self._bundle_pool(getattr(w, "pg_bundle", None))
                        if pool is not None:
                            w.resources.add_to(pool)
                    if w.tpu_chips:
                        self._tpu_chips_free.extend(w.tpu_chips)
                        w.tpu_chips = []
                    self._wake_lease_waiters()
                    if w.leased:
                        try:
                            await self._gcs.call(
                                "report_worker_death",
                                node_id=self.node_id.binary(),
                                worker_address=w.address,
                                reason=f"exit code {code}",
                            )
                        except Exception:
                            pass
                elif (not w.leased and w.ready.is_set()
                      and time.monotonic() - w.last_idle > idle_ttl):
                    # Trim warm pool beyond the configured size.
                    idle = [x for x in self.workers.values()
                            if not x.leased and x.env_key == w.env_key]
                    if len(idle) > cfg.idle_worker_pool_size:
                        w.proc.terminate()
            self.store.reclaim_stale(120)


def main() -> None:  # pragma: no cover - exercised via subprocess
    import argparse
    import json

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--node-name", default="")
    parser.add_argument("--labels", default="")
    args = parser.parse_args()

    resources = json.loads(args.resources) if args.resources else None

    async def _run():
        import signal

        nodelet = Nodelet(
            (args.gcs_host, args.gcs_port),
            args.session_dir,
            host=args.host,
            port=args.port,
            resources=resources,
            object_store_memory=args.object_store_memory or None,
            node_name=args.node_name,
            labels=json.loads(args.labels) if args.labels else None,
        )
        await nodelet.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        try:
            # Reap workers before exiting — otherwise they leak past the
            # session. Bounded: a hung teardown must not outlive the
            # driver's kill grace period with the arena still on disk.
            await asyncio.wait_for(nodelet.stop(), 8)
        except Exception:
            pass
        finally:
            for p in (nodelet.store_path, nodelet.store_path + ".pid"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    asyncio.run(_run())


if __name__ == "__main__":
    main()
